"""Tests for typed metadata records, user registry, tool result layers and
the new jtmodule twins (clip/combine_channels/expand/shrink/mip)."""

import numpy as np
import pandas as pd
import pytest

from tmlibrary_tpu.models.metadata import (
    ChannelImageMetadata,
    ChannelLayer,
    IllumstatsImageMetadata,
    ImageFileMapping,
    ImageMetadata,
    PyramidTileMetadata,
)
from tmlibrary_tpu.models.user import ExperimentShare, User, UserRegistry


def test_image_metadata_round_trip():
    m = ChannelImageMetadata(
        plate=1, well="B03", site_y=2, site_x=4, channel="DAPI", is_corrected=True
    )
    d = m.to_dict()
    back = ChannelImageMetadata.from_dict(d)
    assert back == m
    # base-class round trip ignores unknown keys
    assert ImageMetadata.from_dict({**d, "bogus": 1}).well == "B03"


def test_illumstats_metadata_round_trip():
    m = IllumstatsImageMetadata(channel="GFP", cycle=2, n_sites=384, is_smoothed=True)
    assert IllumstatsImageMetadata.from_dict(m.to_dict()) == m


def test_pyramid_tile_metadata_filename():
    t = PyramidTileMetadata(level=3, row=2, col=7, channel="channel00")
    assert t.filename() == "channel00/3/2_7.png"


def test_channel_layer_grid():
    # 1024x768 mosaic, 256px tiles, 3 levels: max_zoom=2 full res
    layer = ChannelLayer(channel="c", height=1024, width=768, max_zoom=2)
    assert layer.grid(2) == (4, 3)
    assert layer.grid(1) == (2, 2)  # 512x384
    assert layer.grid(0) == (1, 1)  # 256x192
    with pytest.raises(ValueError):
        layer.grid(3)
    assert ChannelLayer.from_dict(layer.to_dict()) == layer


def test_image_file_mapping_round_trip():
    m = ImageFileMapping(path="a.tif", site_index=7, channel=1, series=2, plane=3)
    assert ImageFileMapping.from_dict(m.to_dict()) == m


def test_user_registry(tmp_path):
    reg = UserRegistry(tmp_path / "users.json")
    reg.add_user(User("alice", "a@x"))
    reg.add_user(User("bob"))
    reg.set_owner("exp1", "alice")
    reg.share(ExperimentShare("exp1", "bob", write=False))
    assert reg.can_read("exp1", "bob") and not reg.can_write("exp1", "bob")
    assert reg.can_write("exp1", "alice")
    # persisted
    reg2 = UserRegistry(tmp_path / "users.json")
    assert [u.name for u in reg2.users()] == ["alice", "bob"]
    assert reg2.can_read("exp1", "bob")
    with pytest.raises(KeyError):
        reg2.set_owner("exp2", "nobody")


def test_tool_result_label_layers():
    from tmlibrary_tpu.tools.base import (
        ContinuousLabelLayer,
        Plot,
        ScalarLabelLayer,
        SupervisedClassifierLabelLayer,
        ToolResult,
    )

    df = pd.DataFrame(
        {"site_index": [0, 0, 1], "label": [1, 2, 1], "value": [0.5, 1.5, 2.5]}
    )
    cont = ToolResult("heatmap", "cells", "continuous", df)
    layer = cont.label_layer()
    assert isinstance(layer, ContinuousLabelLayer)
    assert layer.value_range() == (0.5, 2.5)

    cat = ToolResult(
        "classification", "cells", "categorical", df, attributes={"classes": ["a", "b"]}
    )
    sup = cat.label_layer()
    assert isinstance(sup, SupervisedClassifierLabelLayer)
    assert sup.classes == ["a", "b"]

    scal = ToolResult("clustering", "cells", "categorical", df).label_layer()
    assert type(scal) is ScalarLabelLayer
    assert scal.unique_values() == [0.5, 1.5, 2.5]

    p = Plot("scatter", {"data": [1, 2]})
    assert Plot.from_json(p.to_json()) == p


def test_tool_result_save_includes_plots(tmp_path):
    import json

    from tmlibrary_tpu.tools.base import Plot, ToolResult

    df = pd.DataFrame({"site_index": [0], "label": [1], "value": [1.0]})
    res = ToolResult("t", "cells", "continuous", df, plots=[Plot("bar", {"x": [1]})])
    res.save(tmp_path / "r0")
    meta = json.loads((tmp_path / "r0" / "result.json").read_text())
    assert meta["plots"] == [{"type": "bar", "figure": {"x": [1]}}]


def test_image_join_grid():
    import jax.numpy as jnp

    from tmlibrary_tpu.models.image import ChannelImage

    tiles = [
        ChannelImage(jnp.full((2, 3), i, jnp.float32), {"site": i}) for i in range(6)
    ]
    mosaic = ChannelImage.join(tiles, 2, 3)
    assert mosaic.shape == (4, 9)
    assert isinstance(mosaic, ChannelImage)
    np.testing.assert_array_equal(np.asarray(mosaic.array[:2, :3]), np.zeros((2, 3)))
    np.testing.assert_array_equal(np.asarray(mosaic.array[2:, 6:]), np.full((2, 3), 5))


def test_new_modules():
    import jax.numpy as jnp

    from tmlibrary_tpu.jterator.modules import get_module

    img = jnp.asarray(np.arange(16, dtype=np.float32).reshape(4, 4))
    out = get_module("clip")(img, lower=2.0, upper=10.0)["clipped_image"]
    assert float(out.min()) == 2.0 and float(out.max()) == 10.0

    comb = get_module("combine_channels")(img, img, weight_1=0.5, weight_2=0.5)
    np.testing.assert_allclose(np.asarray(comb["combined_image"]), np.asarray(img))

    lab = jnp.zeros((8, 8), jnp.int32).at[3:5, 3:5].set(1)
    grown = get_module("expand")(lab, n=1)["expanded_image"]
    assert int((grown > 0).sum()) > int((lab > 0).sum())
    shrunk = get_module("shrink")(grown, n=1)["shrunken_image"]
    assert int((shrunk > 0).sum()) < int((grown > 0).sum())

    stack = jnp.stack([img, 2 * img, 0.5 * img])
    np.testing.assert_allclose(
        np.asarray(get_module("mip")(stack)["mip_image"]), np.asarray(2 * img)
    )


def test_channel_layer_grid_odd_sizes_match_pyramid_levels():
    """grid() must follow the illuminati ceil-halving chain exactly."""
    import jax.numpy as jnp

    from tmlibrary_tpu.ops.pyramid import cut_tiles, pyramid_levels

    mosaic = jnp.zeros((513, 290), jnp.float32)
    levels = pyramid_levels(mosaic)
    layer = ChannelLayer(
        channel="c", height=513, width=290, max_zoom=len(levels) - 1
    )
    for li, lvl in enumerate(levels):
        zoom = len(levels) - 1 - li
        tiles = cut_tiles(np.asarray(lvl, np.uint8))
        rows = max(t[0] for t in tiles) + 1
        cols = max(t[1] for t in tiles) + 1
        assert layer.grid(zoom) == (rows, cols), (zoom, lvl.shape)


def test_production_scale_manifest_planning():
    """A full 384-well / 6-site / 5-channel plate's metadata path
    (manifest build, JSON round trip, site enumeration, batch planning)
    stays trivially fast — guards against quadratic blowups as the
    models grow."""
    import time

    from tmlibrary_tpu.models.experiment import grid_experiment
    from tmlibrary_tpu.utils import create_partitions

    t0 = time.perf_counter()
    exp = grid_experiment(
        "big", well_rows=16, well_cols=24, sites_per_well=(2, 3),
        channel_names=("DAPI", "Actin", "Tubulin", "ER", "Mito"),
        site_shape=(2160, 2560),
    )
    assert exp.n_sites == 384 * 6
    exp2 = type(exp).from_dict(exp.to_dict())
    assert exp2 == exp
    refs = list(exp.sites())
    assert len(refs) == 2304
    assert len(create_partitions(list(range(exp.n_sites)), 64)) == 36
    # whole path is milliseconds; 5 s leaves two orders of headroom
    assert time.perf_counter() - t0 < 5.0
