"""2-D spatially-sharded distributed CC + halo ops vs scipy golden.

The mosaic path sharded over BOTH spatial axes (mesh rows x cols): one
object may now cross horizontal seams, vertical seams, and — the case a
1-D layout never hits — the corner where four shards meet, touching only
diagonally.  Everything must stay bit-identical to ``scipy.ndimage.label``
/ the single-device ops on the gathered mosaic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.ndimage as ndi
from jax.sharding import Mesh

from tmlibrary_tpu.errors import ShardingError
from tmlibrary_tpu.parallel.halo import (
    sharded_gaussian_smooth_2d,
    sharded_halo_map_2d,
)
from tmlibrary_tpu.parallel.label import (
    distributed_connected_components,
    distributed_connected_components_2d,
    sharded_segment_mosaic,
    sharded_segment_mosaic_2d,
)


@pytest.fixture
def mesh42(devices):
    return Mesh(np.asarray(devices).reshape(4, 2), ("rows", "cols"))


@pytest.fixture
def mesh24(devices):
    return Mesh(np.asarray(devices).reshape(2, 4), ("rows", "cols"))


def _golden(mask, connectivity):
    structure = ndi.generate_binary_structure(2, 1 if connectivity == 4 else 2)
    return ndi.label(mask, structure)


@pytest.mark.parametrize("connectivity", [4, 8])
def test_random_mask_matches_scipy_2d(mesh42, rng, connectivity):
    mask = rng.random((64, 48)) > 0.65
    labels, count = distributed_connected_components_2d(
        mask, mesh42, connectivity=connectivity
    )
    golden, n = _golden(mask, connectivity)
    assert int(count) == n
    assert np.array_equal(np.asarray(labels), golden)


def test_corner_diagonal_adjacency(mesh42):
    """Two pixels touching ONLY diagonally across the four-shard corner:
    one component under 8-connectivity, two under 4 — the case that
    requires the corner-extended seam exchange."""
    mask = np.zeros((64, 48), bool)
    # shard tiles are 16x24: (15, 23) is the bottom-right pixel of tile
    # (0, 0); (16, 24) the top-left pixel of tile (1, 1)
    mask[15, 23] = mask[16, 24] = True
    labels, count = distributed_connected_components_2d(mask, mesh42, 8)
    assert int(count) == 1
    lab = np.asarray(labels)
    assert lab[15, 23] == lab[16, 24] == 1
    labels4, count4 = distributed_connected_components_2d(mask, mesh42, 4)
    assert int(count4) == 2
    # the anti-diagonal corner too: (16, 23) bottom-left of tile (1, 0)
    # up-right to (15, 24)? use fresh pixels inside the same tiles
    mask = np.zeros((64, 48), bool)
    mask[16, 23] = mask[15, 24] = True
    labels, count = distributed_connected_components_2d(mask, mesh42, 8)
    assert int(count) == 1


def test_object_spanning_all_eight_shards(mesh42):
    """A plus-shaped band crossing every seam converges to one id."""
    mask = np.zeros((64, 48), bool)
    mask[:, 22:26] = True
    mask[30:34, :] = True
    labels, count = distributed_connected_components_2d(mask, mesh42)
    assert int(count) == 1
    assert set(np.unique(np.asarray(labels))) == {0, 1}


def test_mesh_shape_invariance(mesh42, mesh24, devices, rng):
    """The same mask labels identically on (4,2), (2,4), 1-D (8,) and a
    single device — the layout is an implementation detail."""
    mask = rng.random((64, 64)) > 0.6
    golden, n = _golden(mask, 8)
    l42, c42 = distributed_connected_components_2d(mask, mesh42)
    l24, c24 = distributed_connected_components_2d(mask, mesh24)
    mesh1d = Mesh(np.asarray(devices), ("rows",))
    l1d, c1d = distributed_connected_components(mask, mesh1d)
    assert int(c42) == int(c24) == int(c1d) == n
    assert np.array_equal(np.asarray(l42), golden)
    assert np.array_equal(np.asarray(l24), golden)
    assert np.array_equal(np.asarray(l1d), golden)


def test_dims_must_divide(mesh42):
    with pytest.raises(ShardingError):
        distributed_connected_components_2d(np.zeros((64, 47), bool), mesh42)
    with pytest.raises(ShardingError):
        distributed_connected_components_2d(np.zeros((63, 48), bool), mesh42)


def test_root_overflow_detected_2d(mesh42):
    mask = np.zeros((64, 48), bool)
    mask[::2, ::2] = True  # 16x24/4 = isolated pixels per shard > bound
    with pytest.raises(ShardingError):
        distributed_connected_components_2d(
            mask, mesh42, max_roots_per_shard=64
        )


def test_sharded_gaussian_smooth_2d_bit_identical(mesh42, rng):
    from tmlibrary_tpu.ops.smooth import gaussian_smooth

    img = rng.random((64, 48)).astype(np.float32)
    out = sharded_gaussian_smooth_2d(img, mesh42, sigma=1.5)
    ref = jax.jit(lambda x: gaussian_smooth(x, 1.5))(jnp.asarray(img))
    assert np.array_equal(np.asarray(out), np.asarray(ref))


def test_sharded_halo_map_2d_dims_must_divide(mesh42):
    with pytest.raises(ShardingError):
        sharded_halo_map_2d(lambda x: x, np.zeros((64, 45)), mesh42, 1)


def test_distributed_watershed_2d_bit_identical(mesh42, mesh24, rng):
    """2-D-sharded watershed == single-device watershed on the gathered
    mosaic, tie-breaks included (zero-filled 1-pixel halos per adopt
    step, corners carried by the two-step exchange)."""
    from tmlibrary_tpu.ops.label import connected_components
    from tmlibrary_tpu.ops.segment_secondary import watershed_from_seeds
    from tmlibrary_tpu.parallel.label import (
        distributed_watershed_from_seeds,
        distributed_watershed_from_seeds_2d,
    )

    yy, xx = np.mgrid[0:64, 0:48]
    img = rng.normal(100, 10, (64, 48)).astype(np.float32)
    # one basin dead on the center four-shard corner (tiles are 16x24)
    for cy, cx in ((8, 10), (32, 24), (52, 12), (36, 40)):
        img += 2000 * np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / 30.0)
    seeds_mask = img > 1500
    seeds = np.asarray(connected_components(jnp.asarray(seeds_mask))[0])
    mask = img > 300

    golden = np.asarray(
        watershed_from_seeds(jnp.asarray(img), jnp.asarray(seeds),
                             jnp.asarray(mask), n_levels=8, method="xla")
    )
    for mesh in (mesh42, mesh24):
        sharded = np.asarray(
            distributed_watershed_from_seeds_2d(
                img, seeds, mask, mesh, n_levels=8
            )
        )
        assert np.array_equal(sharded, golden)
    assert golden.max() > 0
    # and the 1-D path agrees on the same inputs
    mesh1d = Mesh(np.asarray(mesh42.devices).reshape(-1), ("rows",))
    one_d = np.asarray(
        distributed_watershed_from_seeds(img, seeds, mask, mesh1d, n_levels=8)
    )
    assert np.array_equal(one_d, golden)


def test_distributed_watershed_2d_dims_must_divide(mesh42):
    from tmlibrary_tpu.parallel.label import (
        distributed_watershed_from_seeds_2d,
    )

    bad = np.zeros((63, 48), np.float32)
    with pytest.raises(ShardingError):
        distributed_watershed_from_seeds_2d(
            bad, np.zeros((63, 48), np.int32), np.zeros((63, 48), bool),
            mesh42,
        )


def test_sharded_segment_mosaic_2d_end_to_end(mesh42, mesh24, rng):
    """Blob mosaic: smooth + global otsu + 2-D CC matches the 1-D sharded
    path (itself scipy-golden-tested) exactly."""
    img = np.zeros((64, 64), np.float32)
    yy, xx = np.mgrid[:64, :64]
    for cy, cx in [(10, 12), (31, 33), (50, 20), (18, 52), (32, 0)]:
        img += np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2) / 18.0))
    img += rng.normal(0, 0.02, img.shape).astype(np.float32)
    l2d, c2d = sharded_segment_mosaic_2d(img, mesh42, sigma=1.5)
    mesh1d = Mesh(np.asarray(mesh42.devices).reshape(-1), ("rows",))
    l1d, c1d = sharded_segment_mosaic(img, mesh1d, sigma=1.5)
    assert int(c2d) == int(c1d) > 0
    assert np.array_equal(np.asarray(l2d), np.asarray(l1d))
    l24, c24 = sharded_segment_mosaic_2d(img, mesh24, sigma=1.5)
    assert int(c24) == int(c2d)
    assert np.array_equal(np.asarray(l24), np.asarray(l2d))
