import numpy as np
import pytest

from tmlibrary_tpu.errors import StoreError
from tmlibrary_tpu.models.experiment import grid_experiment
from tmlibrary_tpu.models.store import ExperimentStore


@pytest.fixture
def experiment():
    return grid_experiment(
        name="t",
        well_rows=2,
        well_cols=2,
        sites_per_well=(2, 2),
        channel_names=("DAPI", "GFP"),
        site_shape=(32, 32),
    )


def test_manifest_roundtrip(tmp_path, experiment):
    path = tmp_path / "manifest.json"
    experiment.save(path)
    loaded = type(experiment).load(path)
    assert loaded == experiment
    assert loaded.n_sites == 16
    assert loaded.n_channels == 2


def test_well_names():
    exp = grid_experiment(well_rows=3, well_cols=12)
    names = {w.name for p in exp.plates for w in p.wells}
    assert "A01" in names and "C12" in names


def test_site_enumeration_order(experiment):
    refs = list(experiment.sites())
    assert len(refs) == 16
    # canonical order: wells row-major, sites row-major within well
    assert refs[0].as_tuple() == ("plate00", 0, 0, 0, 0)
    assert refs[1].as_tuple() == ("plate00", 0, 0, 0, 1)
    assert refs[4].as_tuple() == ("plate00", 0, 1, 0, 0)


def test_store_pixel_roundtrip(tmp_path, experiment, rng):
    store = ExperimentStore.create(tmp_path / "exp", experiment)
    pixels = rng.integers(0, 65535, size=(16, 32, 32), dtype=np.uint16)
    store.write_sites(pixels, list(range(16)), channel=0)
    got = store.read_sites(list(range(16)), channel=0)
    np.testing.assert_array_equal(got, pixels)
    # partial batch read
    got2 = store.read_sites([3, 7, 11], channel=0)
    np.testing.assert_array_equal(got2, pixels[[3, 7, 11]])


def test_store_reopen(tmp_path, experiment, rng):
    store = ExperimentStore.create(tmp_path / "exp", experiment)
    pixels = rng.integers(0, 100, size=(4, 32, 32), dtype=np.uint16)
    store.write_sites(pixels, [0, 1, 2, 3], channel=1)
    store2 = ExperimentStore.open(tmp_path / "exp")
    assert store2.experiment == experiment
    np.testing.assert_array_equal(store2.read_sites([0, 1, 2, 3], channel=1), pixels)


def test_store_missing_plane(tmp_path, experiment):
    store = ExperimentStore.create(tmp_path / "exp", experiment)
    with pytest.raises(StoreError):
        store.read_sites([0], channel=0)


def test_illumstats_roundtrip(tmp_path, experiment, rng):
    store = ExperimentStore.create(tmp_path / "exp", experiment)
    stats = {
        "mean_log": rng.random((32, 32)).astype(np.float32),
        "std_log": rng.random((32, 32)).astype(np.float32),
        "n": np.asarray(16),
    }
    store.write_illumstats(stats, channel=0)
    assert store.has_illumstats(channel=0)
    got = store.read_illumstats(channel=0)
    np.testing.assert_array_equal(got["mean_log"], stats["mean_log"])
    assert int(got["n"]) == 16


def test_labels_and_features(tmp_path, experiment, rng):
    import pandas as pd

    store = ExperimentStore.create(tmp_path / "exp", experiment)
    labels = rng.integers(0, 5, size=(16, 32, 32)).astype(np.int32)
    store.write_labels(labels, list(range(16)), "nuclei")
    got = store.read_labels(None, "nuclei")
    np.testing.assert_array_equal(got, labels)
    assert store.list_objects() == ["nuclei"]

    df = pd.DataFrame({"site": [0, 0], "label": [1, 2], "area": [10.0, 20.0]})
    store.append_features("nuclei", df, shard="batch000")
    # idempotent re-write of the same shard
    store.append_features("nuclei", df, shard="batch000")
    read = store.read_features("nuclei")
    assert len(read) == 2


def test_shifts_roundtrip(tmp_path, experiment):
    store = ExperimentStore.create(tmp_path / "exp", experiment)
    shifts = np.array([[1, -2]] * 16, dtype=np.int32)
    store.write_shifts(shifts, cycle=1)
    np.testing.assert_array_equal(store.read_shifts(1), shifts)
    store.write_intersection({"top": 2, "bottom": 1, "left": 0, "right": 2})
    assert store.read_intersection()["top"] == 2


def test_export_illumstats_hdf5(tmp_path):
    """Reference-compat HDF5 export of a channel's illumination stats
    (IllumstatsFile layout), readable back via DatasetReader."""
    import numpy as np

    from tmlibrary_tpu.models.experiment import grid_experiment
    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.readers import DatasetReader

    exp = grid_experiment("h5", well_rows=1, well_cols=1,
                          sites_per_well=(1, 1), channel_names=("DAPI",),
                          site_shape=(8, 8))
    store = ExperimentStore.create(tmp_path / "exp", exp)
    rng = np.random.default_rng(0)
    stats = {
        "mean_log": rng.random((8, 8)).astype(np.float32),
        "std_log": rng.random((8, 8)).astype(np.float32),
        "percentile_keys": np.asarray([0.1, 50.0, 99.9], np.float32),
        "percentile_values": np.asarray([10.0, 500.0, 4000.0], np.float32),
        "n": np.asarray(16.0, np.float32),
    }
    store.write_illumstats(stats, channel=0)
    out = tmp_path / "stats.h5"
    store.export_illumstats_hdf5(out, channel=0)
    with DatasetReader(out) as r:
        np.testing.assert_array_equal(r.read("stats/mean"), stats["mean_log"])
        np.testing.assert_array_equal(r.read("stats/std"), stats["std_log"])
        np.testing.assert_array_equal(
            r.read("stats/percentiles/keys"), stats["percentile_keys"]
        )
        assert float(np.asarray(r.read("stats/n"))) == 16.0


def test_export_illumstats_hdf5_snapshots_and_validates(tmp_path):
    """Re-export replaces the file wholesale (no stale datasets), and a
    stats dict without 'n' fails instead of fabricating a sample count."""
    import numpy as np
    import pytest

    from tmlibrary_tpu.models.experiment import grid_experiment
    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.readers import DatasetReader

    exp = grid_experiment("h5b", well_rows=1, well_cols=1,
                          sites_per_well=(1, 1), channel_names=("DAPI",),
                          site_shape=(8, 8))
    store = ExperimentStore.create(tmp_path / "exp", exp)
    base = {
        "mean_log": np.zeros((8, 8), np.float32),
        "std_log": np.ones((8, 8), np.float32),
        "n": np.asarray(4.0, np.float32),
    }
    out = tmp_path / "stats.h5"
    store.write_illumstats(
        {**base,
         "percentile_keys": np.asarray([50.0], np.float32),
         "percentile_values": np.asarray([100.0], np.float32)},
        channel=0,
    )
    store.export_illumstats_hdf5(out, channel=0)
    # second export WITHOUT percentiles must not leave the old ones behind
    store.write_illumstats(base, channel=0)
    store.export_illumstats_hdf5(out, channel=0)
    import h5py

    with h5py.File(out, "r") as f:
        assert "stats/percentiles" not in f
        assert float(f["stats/n"][()]) == 4.0

    # missing 'n': validated BEFORE touching the file — the previous
    # good export survives intact
    store.write_illumstats({k: v for k, v in base.items() if k != "n"},
                           channel=0)
    from tmlibrary_tpu.errors import StoreError

    with pytest.raises(StoreError, match="required fields"):
        store.export_illumstats_hdf5(out, channel=0)
    with h5py.File(out, "r") as f:
        assert float(f["stats/n"][()]) == 4.0  # untouched


def test_cli_export_illumstats(tmp_path):
    from tmlibrary_tpu.cli import main
    from tmlibrary_tpu.models.experiment import grid_experiment
    from tmlibrary_tpu.models.store import ExperimentStore

    exp = grid_experiment("h5c", well_rows=1, well_cols=1,
                          sites_per_well=(1, 1), channel_names=("DAPI",),
                          site_shape=(8, 8))
    store = ExperimentStore.create(tmp_path / "exp", exp)
    store.write_illumstats({
        "mean_log": np.zeros((8, 8), np.float32),
        "std_log": np.ones((8, 8), np.float32),
        "n": np.asarray(1.0, np.float32),
    }, channel=0)
    out = tmp_path / "s.h5"
    assert main(["export", "--root", str(store.root),
                 "--illumstats", "0", "--out", str(out)]) == 0
    assert out.exists()
    # neither --objects nor --illumstats is an error
    assert main(["export", "--root", str(store.root),
                 "--out", str(tmp_path / "x.csv")]) == 1


def test_cli_export_images_roundtrip(tmp_path):
    """tmx export --images writes uint16 TIFFs whose names re-ingest
    through the default filename handler; --correct/--align apply the
    stored preprocessing."""
    import cv2

    from tmlibrary_tpu.cli import main
    from tmlibrary_tpu.models.experiment import grid_experiment
    from tmlibrary_tpu.models.store import ExperimentStore

    exp = grid_experiment("imgs", well_rows=1, well_cols=2,
                          sites_per_well=(1, 2), channel_names=("DAPI",),
                          site_shape=(16, 16))
    store = ExperimentStore.create(tmp_path / "exp", exp)
    rng = np.random.default_rng(0)
    pixels = rng.integers(100, 5000, (4, 16, 16)).astype(np.uint16)
    store.write_sites(pixels, list(range(4)), channel=0)

    out = tmp_path / "export"
    assert main(["export", "--root", str(store.root), "--images", "0",
                 "--out", str(out)]) == 0
    names = sorted(p.name for p in out.glob("*.tif"))
    assert names == ["A01_s0_DAPI.tif", "A01_s1_DAPI.tif",
                     "A02_s0_DAPI.tif", "A02_s1_DAPI.tif"]
    got = cv2.imread(str(out / "A01_s0_DAPI.tif"), cv2.IMREAD_UNCHANGED)
    np.testing.assert_array_equal(got, pixels[0])

    # --align applies the stored correction roll
    store.write_shifts(np.tile([[2, 0]], (4, 1)).astype(np.int32), cycle=0)
    out2 = tmp_path / "aligned"
    assert main(["export", "--root", str(store.root), "--images", "0",
                 "--align", "--out", str(out2)]) == 0
    got2 = cv2.imread(str(out2 / "A01_s0_DAPI.tif"), cv2.IMREAD_UNCHANGED)
    np.testing.assert_array_equal(got2[2:], pixels[0][:-2])
    assert (got2[:2] == 0).all()

    # mutually exclusive modes
    assert main(["export", "--root", str(store.root), "--images", "0",
                 "--illumstats", "0", "--out", str(out)]) == 1
    # --correct without corilla stats is an error
    assert main(["export", "--root", str(store.root), "--images", "0",
                 "--correct", "--out", str(out)]) == 1


def test_cli_export_images_multi_z_and_reingest(tmp_path):
    """Multi-zplane exports write t/z-tokenized names that re-ingest
    through the default filename handler into an equivalent store."""
    import cv2

    from tmlibrary_tpu.cli import main
    from tmlibrary_tpu.models.experiment import grid_experiment
    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.workflow.registry import get_step

    exp = grid_experiment("z", well_rows=1, well_cols=1,
                          sites_per_well=(1, 2), channel_names=("DAPI",),
                          site_shape=(8, 8), n_zplanes=2)
    store = ExperimentStore.create(tmp_path / "exp", exp)
    rng = np.random.default_rng(1)
    planes = {z: rng.integers(0, 5000, (2, 8, 8)).astype(np.uint16)
              for z in range(2)}
    for z, px in planes.items():
        store.write_sites(px, [0, 1], channel=0, zplane=z)

    out = tmp_path / "export"
    assert main(["export", "--root", str(store.root), "--images", "0",
                 "--out", str(out)]) == 0
    names = sorted(p.name for p in out.glob("*.tif"))
    assert names == ["A01_s0_z0_DAPI.tif", "A01_s0_z1_DAPI.tif",
                     "A01_s1_z0_DAPI.tif", "A01_s1_z1_DAPI.tif"]

    # round trip: metaconfig+imextract over the exported tree
    store2 = ExperimentStore.create(
        tmp_path / "exp2",
        grid_experiment("z2", well_rows=1, well_cols=1,
                        sites_per_well=(1, 1), channel_names=("X",),
                        site_shape=(1, 1)),
    )
    mc = get_step("metaconfig")(store2)
    mc.init({"source_dir": str(out)})
    for i in mc.list_batches():
        mc.run(i)
    mc.collect()
    ie = get_step("imextract")(store2)
    ie.init({})
    for i in ie.list_batches():
        ie.run(i)
    exp2 = ExperimentStore.open(store2.root).experiment
    assert exp2.n_zplanes == 2 and exp2.n_sites == 2
    for z in range(2):
        np.testing.assert_array_equal(
            store2.read_sites([0, 1], channel=0, zplane=z), planes[z]
        )
