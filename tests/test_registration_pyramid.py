import jax.numpy as jnp
import numpy as np

from tmlibrary_tpu.ops.pyramid import (
    TILE_SIZE,
    cut_tiles,
    downsample_2x,
    pyramid_levels,
    to_uint8,
)
from tmlibrary_tpu.ops.registration import (
    batch_phase_correlation,
    intersection_window,
    phase_correlation,
)


def test_phase_correlation_recovers_known_shift(rng):
    base = rng.random((128, 128)).astype(np.float32)
    base = np.asarray(jnp.asarray(base))
    for dy, dx in [(0, 0), (5, -3), (-7, 11), (20, 20)]:
        shifted = np.roll(base, (-dy, -dx), axis=(0, 1))
        gy, gx = phase_correlation(jnp.asarray(base), jnp.asarray(shifted))
        assert (int(gy), int(gx)) == (dy, dx), (dy, dx, int(gy), int(gx))


def test_batch_phase_correlation(rng):
    base = rng.random((4, 64, 64)).astype(np.float32)
    shifts = [(1, 2), (-3, 4), (0, 0), (6, -5)]
    target = np.stack(
        [np.roll(base[i], (-dy, -dx), axis=(0, 1)) for i, (dy, dx) in enumerate(shifts)]
    )
    got = np.asarray(batch_phase_correlation(jnp.asarray(base), jnp.asarray(target)))
    np.testing.assert_array_equal(got, np.asarray(shifts))


def test_intersection_window():
    shifts = np.array([[3, -2], [-1, 4], [0, 0]])
    w = intersection_window(shifts)
    assert w == {"top": 3, "bottom": 1, "left": 4, "right": 2}
    assert intersection_window(np.zeros((0, 2))) == {
        "top": 0, "bottom": 0, "left": 0, "right": 0,
    }


def test_downsample_2x_mean():
    img = jnp.asarray(np.arange(16, dtype=np.float32).reshape(4, 4))
    out = np.asarray(downsample_2x(img))
    np.testing.assert_allclose(out, [[2.5, 4.5], [10.5, 12.5]])


def test_downsample_odd_shape():
    img = jnp.ones((5, 7), jnp.float32)
    out = np.asarray(downsample_2x(img))
    assert out.shape == (3, 4)
    np.testing.assert_allclose(out, 1.0)


def test_n_pyramid_levels_matches_chain(rng):
    from tmlibrary_tpu.ops.pyramid import n_pyramid_levels

    for shape in ((1024, 768), (256, 256), (100, 100), (8192, 8192), (257, 1)):
        mosaic = jnp.zeros(shape, jnp.float32)
        assert n_pyramid_levels(*shape) == len(pyramid_levels(mosaic))


def test_pyramid_levels_chain(rng):
    mosaic = jnp.asarray(rng.random((1024, 768)).astype(np.float32))
    levels = pyramid_levels(mosaic)
    shapes = [l.shape for l in levels]
    assert shapes[0] == (1024, 768)
    assert shapes[1] == (512, 384)
    assert shapes[-1][0] <= TILE_SIZE and shapes[-1][1] <= TILE_SIZE
    # mean preserved through the chain
    np.testing.assert_allclose(
        float(jnp.mean(levels[0])), float(jnp.mean(levels[1])), rtol=1e-3
    )


def test_cut_tiles_pads_edges(rng):
    level = rng.random((300, 520)).astype(np.float32)
    tiles = cut_tiles(level)
    assert set(tiles) == {(r, c) for r in range(2) for c in range(3)}
    np.testing.assert_array_equal(tiles[(0, 0)], level[:256, :256])
    # edge tile zero-padded
    t = tiles[(1, 2)]
    assert t.shape == (256, 256)
    np.testing.assert_array_equal(t[: 300 - 256, : 520 - 512], level[256:, 512:])
    assert t[300 - 256 :, :].sum() == 0


def test_to_uint8_stretch():
    img = jnp.asarray([[0.0, 50.0, 100.0, 200.0]])
    out = np.asarray(to_uint8(img, 50.0, 150.0))
    assert out.dtype == np.uint8
    assert list(out[0]) == [0, 0, 127, 255]


def test_phase_correlation_quality(rng):
    """Quality ~1 for a true circular shift, low for unrelated noise."""
    from tmlibrary_tpu.ops.registration import phase_correlation_quality

    img = rng.normal(100, 30, (64, 64)).astype(np.float32)
    shifted = np.roll(img, (5, -3), axis=(0, 1))
    dy, dx, q = phase_correlation_quality(img, shifted)
    # convention: reference[y, x] = target[y - dy, x - dx]
    assert (int(dy), int(dx)) == (-5, 3)
    assert float(q) > 0.9

    other = rng.normal(100, 30, (64, 64)).astype(np.float32)
    _, _, q_noise = phase_correlation_quality(img, other)
    assert float(q_noise) < 0.2


def test_phase_correlation_subpixel(rng):
    """Matrix-multiply DFT refinement recovers known sub-pixel shifts to
    1/upsample resolution (sign convention matches phase_correlation)."""
    from tmlibrary_tpu.ops.registration import phase_correlation_subpixel

    img = rng.normal(100, 30, (64, 64)).astype(np.float32)

    def fshift(im, dy, dx):
        f = np.fft.fft2(im)
        fy = np.fft.fftfreq(im.shape[0])[:, None]
        fx = np.fft.fftfreq(im.shape[1])[None, :]
        return np.real(np.fft.ifft2(f * np.exp(-2j * np.pi * (fy * dy + fx * dx))))

    for true_dy, true_dx in ((2.3, -1.7), (0.4, 0.0), (-3.8, 2.2)):
        shifted = fshift(img, true_dy, true_dx)
        dy, dx = phase_correlation_subpixel(img, shifted, upsample=20)
        assert abs(float(dy) + true_dy) <= 0.05
        assert abs(float(dx) + true_dx) <= 0.05


def test_pyramid_respects_compute_dtype(monkeypatch):
    """compute_dtype drives the display-only pyramid math: bfloat16
    levels still encode to the same 8-bit tiles for smooth content."""
    from tmlibrary_tpu import config as cfg_mod
    from tmlibrary_tpu.ops.pyramid import pyramid_levels

    mosaic = np.linspace(0, 4000, 512 * 512, dtype=np.float32).reshape(512, 512)
    lv_f32 = pyramid_levels(jnp.asarray(mosaic), n_levels=3)
    monkeypatch.setattr(cfg_mod.cfg, "compute_dtype", "bfloat16")
    lv_bf16 = pyramid_levels(jnp.asarray(mosaic), n_levels=3)
    assert str(lv_bf16[1].dtype) == "bfloat16"
    assert lv_f32[1].dtype == jnp.float32
    # after 8-bit display quantization the chains agree to within the
    # ~8-bit bfloat16 mantissa (a couple of gray counts out of 255)
    for a, b in zip(lv_f32[1:], lv_bf16[1:]):
        qa = np.asarray(jnp.asarray(a, jnp.float32) / 4000.0 * 255).astype(np.uint8)
        qb = np.asarray(jnp.asarray(b, jnp.float32) / 4000.0 * 255).astype(np.uint8)
        assert np.abs(qa.astype(int) - qb.astype(int)).max() <= 2
