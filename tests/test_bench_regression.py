"""The bench-regression sentinel: ``perf.compare_history`` verdicts and
the pinned exit codes of ``scripts/bench_regression.py`` (0 ok/improvement,
1 regression, 2 stale, 3 no baseline), plus the re-capture queue handoff
into ``scripts/tpu_watch.py``."""
import json
import os
import subprocess
import sys
import time

import pytest

from tmlibrary_tpu import perf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SENTINEL = os.path.join(REPO, "scripts", "bench_regression.py")

NOW = 1_800_000_000.0


def _rec(value, config="3", metric="jterator_sites_per_sec_per_chip",
         backend="tpu", age_h=1.0, sweep=False, **extra):
    rec = {
        "metric": metric, "config": config, "backend": backend,
        "value": value, "recorded_at_unix": NOW - age_h * 3600.0,
        "recorded_at": f"{age_h}h ago",
    }
    if sweep:
        rec["sweep"] = True
    rec.update(extra)
    return rec


def _write(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


# ------------------------------------------------------- compare_history
def test_compare_improvement_and_ok():
    hist = [_rec(100.0, age_h=30), _rec(120.0, age_h=1)]
    v = perf.compare_history(hist, now=NOW)
    assert (v["status"], v["exit_code"]) == ("improvement", perf.EXIT_OK)
    assert v["delta_frac"] == pytest.approx(0.2)
    assert v["recapture"] == []

    hist = [_rec(100.0, age_h=30), _rec(99.0, age_h=1)]
    v = perf.compare_history(hist, now=NOW)
    assert (v["status"], v["exit_code"]) == ("ok", perf.EXIT_OK)


def test_compare_regression():
    hist = [_rec(100.0, age_h=30), _rec(80.0, age_h=1)]
    v = perf.compare_history(hist, now=NOW)
    assert (v["status"], v["exit_code"]) == ("regression",
                                             perf.EXIT_REGRESSION)
    assert v["delta_frac"] == pytest.approx(-0.2)
    assert v["recapture"] == ["bench:3"]
    assert v["baseline"]["value"] == 100.0


def test_compare_stale_and_regression_outranks_stale():
    hist = [_rec(100.0, age_h=300), _rec(99.0, age_h=200)]
    v = perf.compare_history(hist, stale_hours=72, now=NOW)
    assert (v["status"], v["exit_code"]) == ("stale", perf.EXIT_STALE)
    assert v["recapture"] == ["bench:3"]

    hist = [_rec(100.0, age_h=300), _rec(50.0, age_h=200)]
    v = perf.compare_history(hist, stale_hours=72, now=NOW)
    assert v["exit_code"] == perf.EXIT_REGRESSION  # more actionable


def test_compare_no_baseline():
    v = perf.compare_history([], now=NOW)
    assert v["exit_code"] == perf.EXIT_NO_BASELINE
    # a lone record has nothing comparable before it
    v = perf.compare_history([_rec(100.0)], now=NOW)
    assert (v["status"], v["exit_code"]) == ("no_baseline",
                                             perf.EXIT_NO_BASELINE)
    # backend classes never cross-judge: a CPU rehearsal is not a
    # baseline for a TPU number
    hist = [_rec(500.0, backend="cpu_forced"), _rec(100.0, backend="tpu")]
    v = perf.compare_history(hist, now=NOW)
    assert v["exit_code"] == perf.EXIT_NO_BASELINE


def test_compare_backend_class_collapse():
    # cpu_forced and cpu_fallback are the same evidence class, and
    # tpu_cached counts as hardware
    hist = [_rec(100.0, backend="cpu_forced", age_h=30),
            _rec(120.0, backend="cpu_fallback", age_h=1)]
    assert perf.compare_history(hist, now=NOW)["status"] == "improvement"
    hist = [_rec(100.0, backend="tpu", age_h=30),
            _rec(80.0, backend="tpu_cached", age_h=1)]
    assert perf.compare_history(hist, now=NOW)["status"] == "regression"


def test_compare_filters_and_sweep_label():
    hist = [
        _rec(100.0, config="3", age_h=30),
        _rec(10.0, config="volume", metric="mv", age_h=20),
        _rec(5.0, config="volume", metric="mv", age_h=1, sweep=True),
    ]
    v = perf.compare_history(hist, config="volume", now=NOW)
    assert v["exit_code"] == perf.EXIT_REGRESSION
    assert v["recapture"] == ["sweep:volume"]  # sweep records re-sweep
    # error / non-positive records never participate
    hist = [_rec(100.0, age_h=30), _rec(0.0, age_h=2),
            {**_rec(1.0, age_h=1), "error": "relay died"}]
    v = perf.compare_history(hist, now=NOW)
    assert v["latest"]["value"] == 100.0


def test_compare_baseline_file_pool():
    baseline = [_rec(100.0, age_h=500)]
    hist = [_rec(80.0, age_h=1)]
    v = perf.compare_history(hist, baseline=baseline, now=NOW)
    assert v["exit_code"] == perf.EXIT_REGRESSION
    # in-history mode the same lone record would be no_baseline
    assert perf.compare_history(hist, now=NOW)["exit_code"] == \
        perf.EXIT_NO_BASELINE


# --------------------------------------------- CLI exit codes, pinned
def _run(args, **env):
    proc = subprocess.run(
        [sys.executable, SENTINEL, *args],
        env={**os.environ, **env}, capture_output=True, text=True,
        timeout=120,
    )
    return proc


def _fresh(age_h):
    """recorded_at_unix relative to real now (the CLI judges against
    wall-clock)."""
    return time.time() - age_h * 3600.0


def test_cli_exit_improvement(tmp_path):
    hist = _write(tmp_path / "h.jsonl", [
        {**_rec(100.0), "recorded_at_unix": _fresh(30)},
        {**_rec(120.0), "recorded_at_unix": _fresh(1)},
    ])
    proc = _run(["--history", hist, "--no-queue"])
    assert proc.returncode == 0, proc.stderr
    assert "improvement" in proc.stdout


def test_cli_exit_regression(tmp_path):
    hist = _write(tmp_path / "h.jsonl", [
        {**_rec(100.0), "recorded_at_unix": _fresh(30)},
        {**_rec(80.0), "recorded_at_unix": _fresh(1)},
    ])
    proc = _run(["--history", hist, "--no-queue"])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "regression" in proc.stdout
    assert "bench:3" in proc.stdout


def test_cli_exit_stale(tmp_path):
    hist = _write(tmp_path / "h.jsonl", [
        {**_rec(100.0), "recorded_at_unix": _fresh(300)},
        {**_rec(99.0), "recorded_at_unix": _fresh(200)},
    ])
    proc = _run(["--history", hist, "--no-queue"])
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "stale" in proc.stdout


def test_cli_exit_no_baseline(tmp_path):
    hist = _write(tmp_path / "h.jsonl",
                  [{**_rec(100.0), "recorded_at_unix": _fresh(1)}])
    proc = _run(["--history", hist, "--no-queue"])
    assert proc.returncode == 3, proc.stdout + proc.stderr


def test_cli_absent_and_empty_history_is_friendly_no_baseline(tmp_path):
    """A fresh checkout has no BENCH_HISTORY.jsonl at all (and a touched
    one is empty): both are the pinned exit 3 with a hint naming the
    file, not a crash or a confusing 'no comparable records'."""
    absent = str(tmp_path / "nowhere" / "BENCH_HISTORY.jsonl")
    proc = _run(["--history", absent, "--no-queue"])
    assert proc.returncode == 3, proc.stdout + proc.stderr
    assert "no_baseline" in proc.stdout
    assert "absent" in proc.stdout and absent in proc.stdout

    empty = tmp_path / "BENCH_HISTORY.jsonl"
    empty.touch()
    proc = _run(["--history", str(empty), "--no-queue"])
    assert proc.returncode == 3, proc.stdout + proc.stderr
    assert "empty" in proc.stdout
    # --json keeps the verdict machine-readable on the same path
    proc = _run(["--history", str(empty), "--no-queue", "--json"])
    assert proc.returncode == 3
    verdict = json.loads(proc.stdout)
    assert verdict["status"] == "no_baseline"
    assert verdict["history_records"] == 0
    # BENCH_HISTORY env routes the default path the same way
    proc = _run(["--no-queue"], BENCH_HISTORY=absent)
    assert proc.returncode == 3
    assert "absent" in proc.stdout


def test_workflow_status_survives_absent_bench_history(
        monkeypatch, tmp_path, capsys):
    """``tmx workflow status`` must render (exit 0) when the bench
    history and the on-hardware bench cache are both absent — the
    staleness advisory line just stays silent."""
    from tmlibrary_tpu.cli import main
    from tmlibrary_tpu.models.experiment import Experiment
    from tmlibrary_tpu.models.store import ExperimentStore

    placeholder = Experiment(name="e", plates=[], channels=[],
                             site_height=1, site_width=1)
    store = ExperimentStore.create(tmp_path / "e", placeholder)
    monkeypatch.setenv("BENCH_HISTORY",
                       str(tmp_path / "no" / "BENCH_HISTORY.jsonl"))
    monkeypatch.setenv("BENCH_TPU_CACHE",
                       str(tmp_path / "no" / "BENCH_TPU.json"))
    assert main(["workflow", "status", "--root", str(store.root)]) == 0
    out = capsys.readouterr().out
    assert "bench records stale" not in out


def test_cli_baseline_file_and_json(tmp_path):
    baseline = _write(tmp_path / "b.jsonl",
                      [{**_rec(100.0), "recorded_at_unix": _fresh(500)}])
    hist = _write(tmp_path / "h.jsonl",
                  [{**_rec(150.0), "recorded_at_unix": _fresh(1)}])
    proc = _run(["--history", hist, "--baseline", baseline,
                 "--no-queue", "--json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout)
    assert verdict["status"] == "improvement"
    assert verdict["baseline"]["value"] == 100.0
    # widened threshold turns a small dip into ok (the CI CPU smoke mode)
    hist2 = _write(tmp_path / "h2.jsonl",
                   [{**_rec(70.0), "recorded_at_unix": _fresh(1)}])
    proc = _run(["--history", hist2, "--baseline", baseline,
                 "--threshold", "0.5", "--no-queue"])
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_writes_recapture_queue(tmp_path):
    hist = _write(tmp_path / "h.jsonl", [
        {**_rec(100.0), "recorded_at_unix": _fresh(30)},
        {**_rec(80.0), "recorded_at_unix": _fresh(1)},
    ])
    queue = tmp_path / "RECAPTURE.json"
    proc = _run(["--history", hist, "--queue-out", str(queue)])
    assert proc.returncode == 1
    doc = json.loads(queue.read_text())
    assert doc["items"] == ["bench:3"]
    assert "regression" in doc["reason"]


# ------------------------------------------- tpu_watch queue pickup
def test_tpu_watch_picks_up_validated_labels(monkeypatch, tmp_path):
    monkeypatch.syspath_prepend(REPO)
    from scripts import tpu_watch

    queue = tmp_path / "RECAPTURE.json"
    monkeypatch.setenv("WATCH_RECAPTURE", str(queue))
    monkeypatch.delenv("WATCH_ONLY", raising=False)
    assert tpu_watch.recapture_pending() == []

    perf.write_recapture([
        "bench:3",                  # known bench item
        "sweep:volume",             # known sweep config
        "sweep-capacity:4",         # known capacity-sweep config
        "bench:nonsense",           # unknown: must be ignored
        "sweep-capacity:pyramid",   # not a capacity config: ignored
        "tune:pipeline",            # not a re-capture label shape
    ])
    assert tpu_watch.recapture_pending() == [
        "bench:3", "sweep:volume", "sweep-capacity:4"]

    # a fired capture clears its label; unknown labels stay in the file
    # (harmless) but never reach the watcher
    tpu_watch._clear_recapture("sweep:volume")
    tpu_watch._clear_recapture("sweep-capacity:4")
    assert tpu_watch.recapture_pending() == ["bench:3"]
    tpu_watch._clear_recapture("bench:3")
    assert tpu_watch.recapture_pending() == []
    assert perf.load_recapture() == [
        "bench:nonsense", "sweep-capacity:pyramid", "tune:pipeline"]


def test_all_pending_dedupes_recapture(monkeypatch, tmp_path):
    monkeypatch.syspath_prepend(REPO)
    import bench
    from scripts import tpu_watch

    (tmp_path / "tuning").mkdir()
    monkeypatch.setattr(tpu_watch, "CACHE_PATH",
                        str(tmp_path / "tuning" / "BENCH_TPU.json"))
    monkeypatch.setattr(tpu_watch, "TUNING_PATH",
                        str(tmp_path / "tuning" / "TUNING.json"))
    monkeypatch.setattr(tpu_watch, "PROFILE_PATH",
                        str(tmp_path / "tuning" / "PROFILE_TPU.json"))
    monkeypatch.setenv("TMX_TUNING_JSON",
                       str(tmp_path / "tuning" / "TUNING.json"))
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    monkeypatch.setenv("WATCH_RECAPTURE",
                       str(tmp_path / "tuning" / "RECAPTURE.json"))
    monkeypatch.delenv("WATCH_ONLY", raising=False)

    perf.write_recapture(["bench:3", "sweep:volume"])
    pending = tpu_watch.all_pending()
    # queued re-captures fire early (before the not-yet-done bench items
    # would list them again) and exactly once
    assert pending.count("bench:3") == 1
    assert pending.count("sweep:volume") == 1
    assert pending.index("bench:3") < pending.index("bench:4")
