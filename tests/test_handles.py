"""Typed handle classes: YAML round-trip + per-type array validation.

Reference parity: tmlib/workflow/jterator/handles.py typed handle set.
"""

import numpy as np
import pytest

from tmlibrary_tpu.errors import HandleError
from tmlibrary_tpu.jterator.handles import (
    HandleCollection,
    InputHandle,
    OutputHandle,
)

HANDLES_DICT = {
    "module": "segment_primary",
    "version": "0.1.0",
    "input": [
        {"name": "intensity_image", "type": "IntensityImage", "key": "DAPI"},
        {"name": "threshold_method", "type": "Character", "value": "otsu"},
        {"name": "min_area", "type": "Numeric", "value": 10},
    ],
    "output": [
        {
            "name": "objects",
            "type": "SegmentedObjects",
            "key": "nuclei",
            "objects": "nuclei",
        },
        {"name": "figure", "type": "Figure"},
    ],
}


def test_roundtrip_dict():
    hc = HandleCollection.from_dict(HANDLES_DICT)
    d = hc.to_dict()
    hc2 = HandleCollection.from_dict(d)
    assert hc2 == hc


def test_roundtrip_yaml_file(tmp_path):
    hc = HandleCollection.from_dict(HANDLES_DICT)
    path = tmp_path / "segment.handles.yaml"
    hc.save(path)
    assert HandleCollection.load(path) == hc


def test_intensity_rejects_signed_int():
    h = InputHandle(name="intensity_image", type="IntensityImage", key="x")
    h.validate_array(np.zeros((4, 4), np.uint16))  # ok
    h.validate_array(np.zeros((4, 4), np.float32))  # ok
    with pytest.raises(HandleError):
        h.validate_array(np.zeros((4, 4), np.int32))


def test_label_rejects_float():
    h = InputHandle(name="objects_image", type="LabelImage", key="x")
    h.validate_array(np.zeros((4, 4), np.int32))  # ok
    with pytest.raises(HandleError):
        h.validate_array(np.zeros((4, 4), np.float32))


def test_binary_accepts_bool_and_int():
    h = InputHandle(name="mask", type="BinaryImage", key="x")
    h.validate_array(np.zeros((4, 4), bool))
    h.validate_array(np.zeros((4, 4), np.int32))
    with pytest.raises(HandleError):
        h.validate_array(np.zeros((4, 4), np.float64))


def test_pipeline_rejects_wrong_dtype_at_trace_time():
    """A LabelImage input fed a float image fails at compile, not runtime."""
    import jax.numpy as jnp

    from tmlibrary_tpu.jterator.description import PipelineDescription

    pipe = {
        "description": "bad dtypes",
        "input": {"channels": [{"name": "DAPI"}]},
        "pipeline": [
            {
                "handles": {
                    "module": "measure_morphology",
                    "input": [
                        {
                            "name": "objects_image",
                            "type": "LabelImage",
                            "key": "DAPI",  # float image bound as labels
                        }
                    ],
                    "output": [
                        {
                            "name": "measurements",
                            "type": "Measurement",
                            "objects": "nuclei",
                        }
                    ],
                }
            }
        ],
    }
    from tmlibrary_tpu.jterator.pipeline import ImageAnalysisPipeline

    desc = PipelineDescription.from_dict(pipe)
    fn = ImageAnalysisPipeline(desc, max_objects=8).build_site_fn()
    with pytest.raises(HandleError):
        fn({"DAPI": jnp.zeros((8, 8), jnp.float32)})


def test_output_handle_requires_objects_for_measurement():
    with pytest.raises(HandleError):
        OutputHandle(name="m", type="Measurement")
