"""Keying discipline of the process-level compiled-program cache
(``jterator/pipeline.cached_batch_fn``) and the buffer-donation contract
of ``build_batch_fn``."""

import jax.numpy as jnp
import numpy as np
import pytest

from tmlibrary_tpu.benchmarks import (
    cell_painting_description,
    smooth_threshold_description,
    synthetic_cell_painting_batch,
)
from tmlibrary_tpu.jterator import pipeline as jp
from tmlibrary_tpu.jterator.pipeline import (
    ImageAnalysisPipeline,
    cached_batch_fn,
)


@pytest.fixture(autouse=True)
def _fresh_cache(monkeypatch):
    monkeypatch.setattr(jp, "_BATCH_FN_CACHE", {})
    monkeypatch.delenv("TMX_REDUCTION_STRATEGY", raising=False)
    monkeypatch.delenv("TM_DONATE_BUFFERS", raising=False)


# ------------------------------------------------------------------ keying
def test_identical_descriptions_hit(monkeypatch):
    # two separately-parsed description objects with the same content must
    # share one compiled program
    a = cached_batch_fn(smooth_threshold_description(), 64)
    b = cached_batch_fn(smooth_threshold_description(), 64)
    assert a is b
    assert len(jp._BATCH_FN_CACHE) == 1


def test_max_objects_misses(monkeypatch):
    a = cached_batch_fn(smooth_threshold_description(), 64)
    b = cached_batch_fn(smooth_threshold_description(), 128)
    assert a is not b


def test_window_misses(monkeypatch):
    a = cached_batch_fn(smooth_threshold_description(), 64)
    b = cached_batch_fn(smooth_threshold_description(), 64, (1, 1, 1, 1))
    assert a is not b


def test_donation_flag_misses(monkeypatch):
    a = cached_batch_fn(smooth_threshold_description(), 64, donate=True)
    b = cached_batch_fn(smooth_threshold_description(), 64, donate=False)
    c = cached_batch_fn(smooth_threshold_description(), 64, donate=True)
    assert a is not b
    assert a is c


def test_donation_config_default_keys_cache(monkeypatch):
    a = cached_batch_fn(smooth_threshold_description(), 64)  # default: on
    monkeypatch.setenv("TM_DONATE_BUFFERS", "0")
    b = cached_batch_fn(smooth_threshold_description(), 64)
    assert a is not b
    # and the explicit flag maps onto the same key as the config default
    assert b is cached_batch_fn(smooth_threshold_description(), 64, donate=False)


def test_strategy_request_misses(monkeypatch):
    a = cached_batch_fn(smooth_threshold_description(), 64)
    b = cached_batch_fn(
        smooth_threshold_description(), 64, reduction_strategy="sort"
    )
    assert a is not b
    # env request and explicit parameter resolve to the SAME key
    monkeypatch.setenv("TMX_REDUCTION_STRATEGY", "sort")
    assert b is cached_batch_fn(smooth_threshold_description(), 64)
    # a different env request misses again
    monkeypatch.setenv("TMX_REDUCTION_STRATEGY", "scatter")
    c = cached_batch_fn(smooth_threshold_description(), 64)
    assert c is not a and c is not b


def test_description_content_misses(monkeypatch):
    a = cached_batch_fn(smooth_threshold_description(), 64)
    other = cell_painting_description()
    b = cached_batch_fn(other, 64)
    assert a is not b


# ---------------------------------------------------------------- donation
def test_donated_run_bit_identical_to_undonated():
    """The acceptance pin: donation changes WHERE outputs live, never what
    they are — every leaf of the batch result is bit-identical."""
    desc = cell_painting_description()
    data = synthetic_cell_painting_batch(2, size=64, n_cells=4, seed=3)
    pipe = ImageAnalysisPipeline(desc, max_objects=16)
    shifts = np.zeros((2, 2), np.float32)

    def run(donate):
        fn = pipe.build_batch_fn(donate=donate)
        raw = {k: jnp.asarray(v) for k, v in data.items()}
        shift_arr = jnp.asarray(shifts)
        result = fn(raw, {}, shift_arr)
        return raw, result

    raw_plain, plain = run(donate=False)
    raw_donated, donated = run(donate=True)

    import jax

    leaves_p = jax.tree.leaves(plain)
    leaves_d = jax.tree.leaves(donated)
    assert len(leaves_p) == len(leaves_d) > 0
    for lp, ld in zip(leaves_p, leaves_d):
        np.testing.assert_array_equal(np.asarray(lp), np.asarray(ld))

    # donation is permission, not obligation: XLA only consumes a donated
    # buffer when an output can alias it (this program's outputs are int32
    # labels + feature rows, so the f32 image inputs may survive).  The
    # undonated build must never consume anything.
    assert not any(arr.is_deleted() for arr in raw_plain.values())
