"""The tpu_cached emission path of bench.py (round-2 VERDICT next-step #1):
when the relay is down at driver time, the freshest on-hardware record from
scripts/tpu_watch.py must be emitted — with staleness and the live error —
instead of a sub-baseline CPU number."""
import json
import os
import subprocess
import sys
import tempfile
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(env, timeout=420):
    # history appends go to a throwaway file, never the repo's committed
    # tuning/BENCH_HISTORY.jsonl (tests must not dirty the working tree)
    env = {"BENCH_HISTORY": os.path.join(
        tempfile.mkdtemp(prefix="bench-hist-"), "h.jsonl"), **env}
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env={**os.environ, **env},
        capture_output=True, text=True, timeout=timeout,
    )
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert lines, f"no JSON line: rc={proc.returncode} err={proc.stderr[-400:]}"
    return json.loads(lines[-1])


@pytest.fixture()
def cache_file(tmp_path):
    path = tmp_path / "BENCH_TPU.json"
    record = {
        "metric": "jterator_cell_painting_sites_per_sec_per_chip",
        "value": 236.95,
        "unit": "sites/sec (256x256, 2ch, segment+measure)",
        "vs_baseline": 4.5,
        "backend": "axon",
        "cpu_denominator_sites_per_sec": 52.693,
        "config": "3",
        "batch": 64,
        "max_objects": 64,
    }
    path.write_text(json.dumps({
        "records": {
            "3": {
                "record": record,
                "measured_at": "2026-07-30T05:00:00+00:00",
                "measured_at_unix": time.time() - 7200,
                "provenance": "test fixture",
            }
        }
    }))
    return str(path)


def test_cached_tpu_emitted_when_relay_down(cache_file):
    out = _run_bench({
        "BENCH_TPU_CACHE": cache_file,
        "BENCH_PROBE_TIMEOUT": "3",
        "BENCH_ATTEMPTS": "1",
        # pin the batch to the fixture record's: the REPO's committed
        # TUNING.json otherwise sets the default batch, and a tuned
        # best_batch != 64 makes the knob check reject the fixture —
        # this test would then silently skip forever
        "BENCH_BATCH": "64",
        # break real TPU use even if the relay happens to be alive in CI:
        # probe timeout of 3s fails fast either way on this relay
    })
    if out.get("backend") == "cpu_fallback":
        # with the batch pinned to the fixture record, a cpu_fallback
        # means the cached-emission path itself regressed — fail loudly,
        # don't skip with a misleading "relay alive" message
        pytest.fail(f"cache rejected the pinned fixture record: {out}")
    if out.get("backend") != "tpu_cached":
        # relay alive and fast enough to beat a 3s probe: the live path
        # legitimately wins; nothing to assert about the cache then
        pytest.skip(f"relay answered live: {out.get('backend')}")
    assert out["value"] == 236.95
    assert out["vs_baseline"] == 4.5
    assert out["measured_at"] == "2026-07-30T05:00:00+00:00"
    assert 1.8 < out["cache_age_hours"] < 2.3
    assert "tpu unavailable now" in out["live_error"]
    assert out["provenance"] == "test fixture"


def test_cpu_fallback_when_no_cache(tmp_path):
    out = _run_bench({
        "BENCH_TPU_CACHE": str(tmp_path / "missing.json"),
        "BENCH_PROBE_TIMEOUT": "3",
        "BENCH_ATTEMPTS": "1",
        "BENCH_BATCH": "4",
        "BENCH_REPS": "1",
    })
    if out.get("backend") not in ("cpu_fallback",):
        pytest.skip(f"relay answered live: {out.get('backend')}")
    assert out["value"] > 0
    assert "error" in out


def test_cache_rejected_on_workload_mismatch(cache_file):
    """A cached batch-64 record must not be served for a batch-8 request
    (tune_tpu's sweep would otherwise record one stale number per point)."""
    out = _run_bench({
        "BENCH_TPU_CACHE": cache_file,
        "BENCH_PROBE_TIMEOUT": "3",
        "BENCH_ATTEMPTS": "1",
        "BENCH_BATCH": "8",
        "BENCH_REPS": "1",
    })
    assert out.get("backend") != "tpu_cached"


def test_cache_ignored_for_other_config(cache_file):
    """A cached config-3 record must not satisfy a corilla run."""
    out = _run_bench({
        "BENCH_TPU_CACHE": cache_file,
        "BENCH_PROBE_TIMEOUT": "3",
        "BENCH_ATTEMPTS": "1",
        "BENCH_CONFIG": "corilla",
        "BENCH_SITES": "8",
        "BENCH_CHANNELS": "2",
        "BENCH_REPS": "1",
    })
    assert out.get("backend") != "tpu_cached"
    assert out["metric"] == "corilla_channels_per_sec_per_chip"


def test_cache_defaulted_workload_mismatch_rejected(tmp_path):
    """A fresher record of a DIFFERENT defaulted workload (production
    max_objects=256 variant) must not serve the default request."""
    import time as _time

    path = tmp_path / "BENCH_TPU.json"
    base = {
        "metric": "jterator_cell_painting_sites_per_sec_per_chip",
        "unit": "u", "backend": "axon", "config": "3",
        "batch": 64, "site_size": 256,
    }
    path.write_text(json.dumps({"records": {
        "3": {"record": {**base, "value": 100.0, "vs_baseline": 2.0,
                         "max_objects": 64},
              "measured_at_unix": _time.time() - 7200,
              "measured_at": "old", "provenance": "t"},
        "3@mo256": {"record": {**base, "value": 50.0, "vs_baseline": 1.0,
                               "max_objects": 256},
                    "measured_at_unix": _time.time() - 60,
                    "measured_at": "fresh", "provenance": "t"},
    }}))
    out = _run_bench({
        "BENCH_TPU_CACHE": str(path),
        "BENCH_PROBE_TIMEOUT": "3",
        "BENCH_ATTEMPTS": "1",
        # pin to the fixture records' batch (see the cached-emission
        # test: the repo TUNING.json's best_batch would otherwise make
        # the knob check reject both records and skip forever)
        "BENCH_BATCH": "64",
    })
    if out.get("backend") == "cpu_fallback":
        pytest.fail(f"cache rejected the pinned fixture records: {out}")
    if out.get("backend") != "tpu_cached":
        pytest.skip(f"relay answered live: {out.get('backend')}")
    # the default workload (max_objects=64) must win despite being staler
    assert out["value"] == 100.0
    assert out["max_objects"] == 64


def test_cache_rejected_on_pipeline_depth_mismatch(tmp_path):
    """A depth-8 record must not serve an explicit BENCH_PIPELINE=1
    request (the methodology changes the measured value)."""
    path = tmp_path / "BENCH_TPU.json"
    record = {
        "metric": "jterator_cell_painting_sites_per_sec_per_chip",
        "value": 400.0, "vs_baseline": 7.5, "unit": "u",
        "backend": "axon", "config": "3", "batch": 64,
        "max_objects": 64, "site_size": 256, "pipeline_depth": 8,
    }
    path.write_text(json.dumps({"records": {"3": {
        "record": record, "measured_at": "t",
        "measured_at_unix": time.time() - 60, "provenance": "t",
    }}}))
    out = _run_bench({
        "BENCH_TPU_CACHE": str(path),
        "BENCH_PROBE_TIMEOUT": "3",
        "BENCH_ATTEMPTS": "1",
        "BENCH_BATCH": "64",
        "BENCH_PIPELINE": "1",
        "BENCH_REPS": "1",
    })
    assert out.get("backend") != "tpu_cached"

    # …but the SAME record serves the default request (depth 8 on TPU)
    out2 = _run_bench({
        "BENCH_TPU_CACHE": str(path),
        "BENCH_PROBE_TIMEOUT": "3",
        "BENCH_ATTEMPTS": "1",
        "BENCH_BATCH": "64",
    })
    if out2.get("backend") == "tpu_cached":
        assert out2["value"] == 400.0


def test_cached_record_promotes_newer_sweep_to_headline(tmp_path):
    """A cached config-3 record older than the committed tuning sweep of
    the same workload (same batch) must PROMOTE the sweep's sites/s to
    the headline ``value`` (with the sweep's methodology and provenance)
    — the fresher hardware evidence wins, and the displaced number stays
    alongside as ``superseded_value`` instead of the better one being
    buried under an annotation."""
    cache = tmp_path / "BENCH_TPU.json"
    cache.write_text(json.dumps({"records": {"3": {
        "record": {
            "metric": "jterator_cell_painting_sites_per_sec_per_chip",
            "value": 300.0, "unit": "u", "vs_baseline": 5.0,
            "backend": "axon", "config": "3", "batch": 128,
            "site_size": 256, "max_objects": 64,
            "cpu_denominator_sites_per_sec": 55.0,
        },
        "measured_at": "2026-07-30T23:36:40+00:00",
        "measured_at_unix": time.time() - 7200,
        "provenance": "t",
    }}}))
    tuning = tmp_path / "TUNING.json"
    tuning.write_text(json.dumps({
        "written_by": "scripts/tune_tpu.py write_results",
        "written_at": "2026-08-01T08:33:01+00:00",
        "best_batch": 128, "best_pipeline": 16,
        "pipeline_sweep": {"4": 500.0, "8": 590.0, "16": 606.5},
        "timing_methodology": "pipelined-depth8",
    }))
    out = _run_bench({
        "BENCH_TPU_CACHE": str(cache),
        "TMX_TUNING_JSON": str(tuning),
        "BENCH_PROBE_TIMEOUT": "3",
        "BENCH_ATTEMPTS": "1",
        "BENCH_BATCH": "128",
    })
    if out.get("backend") != "tpu_cached":
        pytest.skip(f"relay answered live (backend={out.get('backend')})")
    # headline promotion
    assert out["value"] == 606.5
    assert out["timing_methodology"] == "pipelined-depth16"
    assert out["pipeline_depth"] == 16
    assert out["measured_at"] == "2026-08-01T08:33:01+00:00"
    assert "tune_tpu" in out["value_provenance"]
    assert out["vs_baseline"] == round(606.5 / 55.0, 2)
    # displaced figure keeps its own provenance
    assert out["superseded_value"] == 300.0
    assert out["superseded_measured_at"] == "2026-07-30T23:36:40+00:00"
    # compat annotation still present for existing consumers
    sweep = out["newer_tuning_sweep"]
    assert sweep["sites_per_sec"] == 606.5
    assert sweep["pipeline_depth"] == 16
    assert sweep["timing_methodology"] == "pipelined-depth16"

    # a record NEWER than the sweep must not be annotated
    rec = json.loads(cache.read_text())
    rec["records"]["3"]["record"]["value"] = 650.0
    rec["records"]["3"]["measured_at"] = "2026-08-02T00:00:00+00:00"
    cache.write_text(json.dumps(rec))
    out = _run_bench({
        "BENCH_TPU_CACHE": str(cache),
        "TMX_TUNING_JSON": str(tuning),
        "BENCH_PROBE_TIMEOUT": "3",
        "BENCH_ATTEMPTS": "1",
        "BENCH_BATCH": "128",
    })
    if out.get("backend") != "tpu_cached":
        pytest.skip(f"relay answered live (backend={out.get('backend')})")
    assert "newer_tuning_sweep" not in out
    assert "superseded_value" not in out
    assert out["value"] == 650.0


def test_cached_record_staleness_recomputed_at_emit(tmp_path):
    """Age and the ``stale`` flag are EMIT-time properties: a 100h-old
    record is stale past the (configurable) threshold, fresh under a
    raised one, and the emission stamps ``emitted_at``."""
    path = tmp_path / "BENCH_TPU.json"
    record = {
        "metric": "jterator_cell_painting_sites_per_sec_per_chip",
        "value": 200.0, "vs_baseline": 4.0, "unit": "u",
        "backend": "axon", "config": "3", "batch": 64,
        "max_objects": 64, "site_size": 256,
    }
    path.write_text(json.dumps({"records": {"3": {
        "record": record, "measured_at": "2026-08-02T00:00:00+00:00",
        "measured_at_unix": time.time() - 100 * 3600, "provenance": "t",
    }}}))
    base = {
        "BENCH_TPU_CACHE": str(path),
        "BENCH_PROBE_TIMEOUT": "3",
        "BENCH_ATTEMPTS": "1",
        "BENCH_BATCH": "64",
    }
    out = _run_bench(base)
    if out.get("backend") != "tpu_cached":
        pytest.skip(f"relay answered live: {out.get('backend')}")
    assert 99.0 < out["cache_age_hours"] < 101.0
    assert out["stale"] is True  # default threshold: 72h
    assert "emitted_at" in out

    out = _run_bench({**base, "BENCH_STALE_HOURS": "200"})
    if out.get("backend") != "tpu_cached":
        pytest.skip(f"relay answered live: {out.get('backend')}")
    assert out["stale"] is False


def test_cached_record_age_recovered_from_iso(tmp_path):
    """Older cache entries carry only the ISO ``measured_at``: the age
    must still be computed (from the parsed stamp) instead of silently
    omitted."""
    import datetime

    path = tmp_path / "BENCH_TPU.json"
    measured = datetime.datetime.now(
        datetime.timezone.utc
    ) - datetime.timedelta(hours=2)
    record = {
        "metric": "jterator_cell_painting_sites_per_sec_per_chip",
        "value": 200.0, "vs_baseline": 4.0, "unit": "u",
        "backend": "axon", "config": "3", "batch": 64,
        "max_objects": 64, "site_size": 256,
    }
    path.write_text(json.dumps({"records": {"3": {
        "record": record,
        "measured_at": measured.isoformat(timespec="seconds"),
        "provenance": "t",  # NOTE: no measured_at_unix
    }}}))
    out = _run_bench({
        "BENCH_TPU_CACHE": str(path),
        "BENCH_PROBE_TIMEOUT": "3",
        "BENCH_ATTEMPTS": "1",
        "BENCH_BATCH": "64",
    })
    if out.get("backend") != "tpu_cached":
        pytest.skip(f"relay answered live: {out.get('backend')}")
    assert 1.8 < out["cache_age_hours"] < 2.3
    assert out["stale"] is False
