import pathlib
import numpy as np
import pytest
import scipy.ndimage as ndi

from tmlibrary_tpu import native


@pytest.fixture(scope="module", autouse=True)
def require_native():
    if not native.available():
        pytest.skip("native library unavailable (no g++?)")


def blobs(rng, shape=(128, 128), n=15, r=6):
    img = np.zeros(shape, bool)
    yy, xx = np.mgrid[0 : shape[0], 0 : shape[1]]
    for y, x in zip(rng.integers(r, shape[0] - r, n), rng.integers(r, shape[1] - r, n)):
        img |= (yy - y) ** 2 + (xx - x) ** 2 <= r**2
    return img


@pytest.mark.parametrize("connectivity", [4, 8])
def test_cc_label_matches_scipy(rng, connectivity):
    mask = blobs(rng)
    structure = ndi.generate_binary_structure(2, 1 if connectivity == 4 else 2)
    expected, n_exp = ndi.label(mask, structure=structure)
    labels, n = native.cc_label_host(mask, connectivity)
    assert n == n_exp
    np.testing.assert_array_equal(labels, expected)


def test_cc_label_snake(rng):
    mask = np.zeros((64, 64), bool)
    for row in range(0, 64, 2):
        mask[row, :] = True
        if row + 1 < 64:
            mask[row + 1, 63 if (row // 2) % 2 == 0 else 0] = True
    labels, n = native.cc_label_host(mask, 8)
    expected, n_exp = ndi.label(mask, ndi.generate_binary_structure(2, 2))
    assert n == n_exp == 1
    np.testing.assert_array_equal(labels, expected)


def test_cc_label_empty():
    labels, n = native.cc_label_host(np.zeros((8, 8), bool), 8)
    assert n == 0 and labels.sum() == 0


def test_trace_boundary_square():
    labels = np.zeros((16, 16), np.int32)
    labels[4:9, 4:9] = 1  # 5x5 square
    pts = native.trace_boundary_host(labels, 1)
    assert pts is not None
    # boundary of a 5x5 square = 16 pixels
    assert len(pts) == 16
    # all points on the perimeter, start at first scan pixel
    assert tuple(pts[0]) == (4, 4)
    for y, x in pts:
        assert labels[y, x] == 1
        assert y in (4, 8) or x in (4, 8)


def test_trace_boundary_single_pixel():
    labels = np.zeros((8, 8), np.int32)
    labels[3, 3] = 7
    pts = native.trace_boundary_host(labels, 7)
    assert len(pts) == 1 and tuple(pts[0]) == (3, 3)


def test_trace_boundary_absent_label():
    labels = np.zeros((8, 8), np.int32)
    pts = native.trace_boundary_host(labels, 5)
    assert len(pts) == 0


def test_trace_matches_mask_outline(rng):
    mask = blobs(rng, n=1, r=10)
    labels, n = native.cc_label_host(mask, 8)
    assert n >= 1
    pts = native.trace_boundary_host(labels, 1)
    # every traced point is a boundary pixel of the object (touches bg)
    for y, x in pts:
        assert labels[y, x] == 1
        neigh = labels[max(0, y - 1) : y + 2, max(0, x - 1) : x + 2]
        assert (neigh == 0).any() or y in (0, 127) or x in (0, 127)


def test_bounding_boxes(rng):
    labels = np.zeros((32, 32), np.int32)
    labels[2:5, 3:9] = 1
    labels[20:30, 15:18] = 2
    boxes = native.bounding_boxes_host(labels, max_label=3)
    np.testing.assert_array_equal(boxes[0], [2, 3, 4, 8])
    np.testing.assert_array_equal(boxes[1], [20, 15, 29, 17])
    np.testing.assert_array_equal(boxes[2], [-1, -1, -1, -1])


def test_native_vs_python_fallback(rng):
    """Fallback path must agree with the native path."""
    mask = blobs(rng)
    native_labels, n1 = native.cc_label_host(mask, 8)
    import tmlibrary_tpu.native as nat

    saved, saved_attempt = nat._lib, nat._load_attempted
    try:
        nat._lib, nat._load_attempted = None, True  # force fallback
        fb_labels, n2 = native.cc_label_host(mask, 8)
    finally:
        nat._lib, nat._load_attempted = saved, saved_attempt
    assert n1 == n2
    np.testing.assert_array_equal(native_labels, fb_labels)


def test_hull_counts_rectangle_solidity_one():
    from tmlibrary_tpu.native import hull_pixel_counts_host, solidity_host

    labels = np.zeros((20, 20), np.int32)
    labels[3:9, 4:14] = 1  # 6x10 rectangle: hull == itself
    counts = hull_pixel_counts_host(labels, 4)
    assert counts[0] == 60
    assert list(counts[1:]) == [0, 0, 0]
    sol = solidity_host(labels, 4)
    np.testing.assert_allclose(sol[0], 1.0)


def test_hull_counts_l_shape_hand_computed():
    from tmlibrary_tpu.native import hull_pixel_counts_host, solidity_host

    # L: column (0..2, 0) plus row (2, 1..2); area 5.  Hull of pixel
    # centers is the triangle (0,0),(2,0),(2,2); pixel centers inside-or-on
    # it: the 5 L pixels + (1,1) on the diagonal edge -> 6.
    labels = np.zeros((5, 5), np.int32)
    labels[0:3, 0] = 1
    labels[2, 1:3] = 1
    counts = hull_pixel_counts_host(labels, 1)
    assert counts[0] == 6
    np.testing.assert_allclose(solidity_host(labels, 1)[0], 5.0 / 6.0)


def test_hull_counts_plus_shape():
    from tmlibrary_tpu.native import hull_pixel_counts_host

    # plus in a 3x3: hull is the diamond over the 4 extremes; corners of
    # the 3x3 are strictly outside -> hull pixel count = 5
    labels = np.zeros((5, 5), np.int32)
    labels[1, 2] = labels[3, 2] = labels[2, 1] = labels[2, 3] = labels[2, 2] = 1
    assert hull_pixel_counts_host(labels, 1)[0] == 5


def test_hull_counts_degenerate_objects():
    from tmlibrary_tpu.native import hull_pixel_counts_host

    labels = np.zeros((8, 8), np.int32)
    labels[1, 1] = 1          # single pixel
    labels[4, 2:7] = 2        # horizontal line
    labels[2:5, 7] = 3        # vertical line (collinear)
    counts = hull_pixel_counts_host(labels, 3)
    assert list(counts) == [1, 5, 3]


def test_hull_native_matches_numpy_fallback(rng):
    import tmlibrary_tpu.native as native
    from tmlibrary_tpu.native import hull_pixel_counts_host

    if not native.available():
        import pytest

        pytest.skip("native library unavailable")
    labels = np.zeros((64, 64), np.int32)
    # random blobby objects
    for lab, (cy, cx, r) in enumerate([(16, 16, 9), (40, 20, 7), (30, 48, 11)], 1):
        yy, xx = np.mgrid[0:64, 0:64]
        blob = ((yy - cy) ** 2 + (xx - cx) ** 2) <= r * r
        jitter = rng.random((64, 64)) > 0.2
        labels[blob & jitter & (labels == 0)] = lab
    got = hull_pixel_counts_host(labels, 8)
    # numpy twin: force the fallback by computing directly
    lib, native._lib = native._lib, None
    attempted = native._load_attempted
    native._load_attempted = True
    try:
        fallback = hull_pixel_counts_host(labels, 8)
    finally:
        native._lib = lib
        native._load_attempted = attempted
    np.testing.assert_array_equal(got, fallback)


# -------------------------------------------------------------- tiff reader
class TestTiffReader:
    """First-party TIFF decode vs cv2 golden (SURVEY.md §3 readers row)."""

    @pytest.mark.parametrize("dtype,hi", [(np.uint8, 255), (np.uint16, 65535)])
    @pytest.mark.parametrize("comp", [1, 5, 32773])  # none, LZW, PackBits
    def test_matches_cv2(self, tmp_path, rng, dtype, hi, comp):
        import cv2

        from tmlibrary_tpu.native import tiff_info, tiff_read

        img = rng.integers(0, hi, (48, 80)).astype(dtype)
        p = tmp_path / "x.tif"
        cv2.imwrite(str(p), img, [cv2.IMWRITE_TIFF_COMPRESSION, comp])
        info = tiff_info(p)
        if info is None:
            pytest.skip("native library unavailable")
        assert info == (1, 48, 80, 8 * dtype().itemsize)
        out = tiff_read(p, 0, 48, 80)
        assert out is not None
        assert np.array_equal(out, img.astype(np.uint16))

    def test_multipage(self, tmp_path, rng):
        import cv2

        from tmlibrary_tpu.native import tiff_info, tiff_read

        pages = [rng.integers(0, 65535, (16, 24)).astype(np.uint16)
                 for _ in range(3)]
        p = tmp_path / "stack.tif"
        cv2.imwritemulti(str(p), pages)
        info = tiff_info(p)
        if info is None:
            pytest.skip("native library unavailable")
        assert info[0] == 3
        for i, page in enumerate(pages):
            out = tiff_read(p, i, 16, 24)
            assert out is not None and np.array_equal(out, page)
        # out-of-range page declines instead of crashing
        assert tiff_read(p, 5, 16, 24) is None

    def test_declines_non_tiff_and_wrong_shape(self, tmp_path, rng):
        import cv2

        from tmlibrary_tpu.native import tiff_read

        img = rng.integers(0, 255, (16, 16)).astype(np.uint8)
        png = tmp_path / "x.png"
        cv2.imwrite(str(png), img)
        assert tiff_read(png, 0, 16, 16) is None  # not a TIFF -> fallback
        tif = tmp_path / "y.tif"
        cv2.imwrite(str(tif), img)
        assert tiff_read(tif, 0, 32, 32) is None  # shape mismatch -> decline


def test_simplify_polygon_square_to_corners():
    """Collinear mid-edge vertices collapse; the 4 corners survive."""
    from tmlibrary_tpu import native

    ring = np.array(
        [[0, 0], [0, 2], [0, 4], [2, 4], [4, 4], [4, 2], [4, 0], [2, 0]],
        np.int32,
    )
    s = native.simplify_polygon_host(ring, 0.5)
    assert s.tolist() == [[0, 0], [0, 4], [4, 4], [4, 0]]
    # tolerance 0 and tiny rings are no-ops
    assert np.array_equal(native.simplify_polygon_host(ring, 0.0), ring)
    tiny = ring[:2]
    assert np.array_equal(native.simplify_polygon_host(tiny, 5.0), tiny)


def test_simplify_polygon_native_matches_numpy(rng):
    """The C++ and numpy implementations agree vertex-for-vertex on real
    traced blob contours at several tolerances."""
    from tmlibrary_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    labels = np.zeros((96, 96), np.int32)
    yy, xx = np.mgrid[0:96, 0:96]
    labels[((yy - 48) / 30.0) ** 2 + ((xx - 48) / 18.0) ** 2 <= 1.0] = 1
    contour = native.trace_boundary_host(labels, 1)
    assert len(contour) > 40
    for tol in (0.5, 1.0, 2.5):
        a = native.simplify_polygon_host(contour, tol)
        b = native._simplify_numpy(contour.astype(np.int32), tol)
        assert np.array_equal(a, b), tol
        assert 3 <= len(a) < len(contour)
    # max deviation of dropped vertices from the simplified ring is
    # bounded by the tolerance (DP guarantee), checked for tol=2.5
    closed = np.vstack([a, a[:1]]).astype(float)

    def seg_dist(p, s0, s1):
        d = s1 - s0
        t = np.clip(np.dot(p - s0, d) / max(np.dot(d, d), 1e-9), 0, 1)
        return np.linalg.norm(p - (s0 + t * d))

    for p in contour.astype(float):
        dmin = min(
            seg_dist(p, closed[i], closed[i + 1]) for i in range(len(closed) - 1)
        )
        assert dmin <= 2.5 + 1e-6


def test_simplify_polygon_never_degenerate():
    """A huge tolerance must still leave >= 3 vertices (valid GeoJSON
    linear ring), re-adding the farthest-from-chord vertex."""
    from tmlibrary_tpu import native

    ring = np.array(
        [[0, 0], [0, 10], [3, 20], [10, 10], [10, 0], [5, 1]], np.int32
    )
    s = native.simplify_polygon_host(ring, 1000.0)
    assert len(s) >= 3
    # the kept vertices are a subset of the input ring
    in_set = {tuple(p) for p in ring.tolist()}
    assert all(tuple(p) in in_set for p in s.tolist())


def test_mosaic_stats_reject_out_of_range_labels():
    """rc=-1 from the native kernels means CORRUPT INPUT (a label
    outside [0, count]), not 'kernel unavailable' — the hosts must raise
    a clear ValueError instead of paying a second plate-scale pass and
    dying with an incidental bincount/ufunc error (round-4 advisor)."""
    from tmlibrary_tpu import native

    lib = native._load()
    if lib is None or not hasattr(lib, "tm_mosaic_intensity"):
        pytest.skip("native library unavailable")
    labels = np.zeros((4, 5), np.int32)
    labels[1, 2] = 9  # > count
    vals = np.ones((4, 5), np.float32)
    with pytest.raises(ValueError, match="outside"):
        native.mosaic_intensity_host(labels, vals, 3)
    with pytest.raises(ValueError, match="outside"):
        native.mosaic_morph_host(labels, 3)


def test_mosaic_stats_native_matches_fallback_and_golden(rng):
    """tm_mosaic_intensity / tm_mosaic_morph vs the chunked-numpy twins
    vs direct per-label numpy — the spatial layout's feature
    accumulators (one C pass instead of an O(H) interpreter loop)."""
    from tmlibrary_tpu import native

    labels = rng.integers(0, 7, (40, 55)).astype(np.int32)
    labels[labels == 5] = 0  # absent id keeps sentinels
    vals = rng.normal(500, 90, (40, 55)).astype(np.float32)
    count = 8  # ids 7..8 absent too

    s, q, mn, mx = native.mosaic_intensity_host(labels, vals, count)
    s2, q2, mn2, mx2 = native._mosaic_intensity_py(labels, vals, count)
    np.testing.assert_allclose(s, s2, rtol=1e-12)
    np.testing.assert_allclose(q, q2, rtol=1e-12)
    np.testing.assert_array_equal(mn, mn2)
    np.testing.assert_array_equal(mx, mx2)

    morph_n = native.mosaic_morph_host(labels, count)
    morph_p = native._mosaic_morph_py(labels, count)
    for got, want in zip(morph_n, morph_p):
        np.testing.assert_array_equal(got, want)

    v64 = vals.astype(np.float64)
    area, cy, cx, ymin, ymax, xmin, xmax = morph_n
    for l in range(count + 1):
        sel = v64[labels == l]
        if not len(sel):
            assert s[l] == 0 and mn[l] == np.inf and mx[l] == -np.inf
            assert area[l] == 0 and ymax[l] == -1 and xmin[l] == 55
            continue
        np.testing.assert_allclose(s[l], sel.sum(), rtol=1e-12)
        np.testing.assert_allclose(q[l], (sel * sel).sum(), rtol=1e-12)
        assert mn[l] == sel.min() and mx[l] == sel.max()
        ys, xs = np.nonzero(labels == l)
        assert area[l] == len(ys)
        assert cy[l] == ys.sum() and cx[l] == xs.sum()
        assert (ymin[l], ymax[l], xmin[l], xmax[l]) == (
            ys.min(), ys.max(), xs.min(), xs.max())


def test_mosaic_morph_fallback_chunks_on_wide_mosaics(rng):
    """A mosaic wide enough to force multiple row blocks through the
    fallback (rows_per = 4M // W) must agree with the native pass."""
    from tmlibrary_tpu import native

    w = (1 << 21) + 7  # rows_per == 1: every row is its own block
    labels = np.zeros((3, w), np.int32)
    labels[0, :100] = 1
    labels[1, 50:200] = 2
    labels[2, w - 5:] = 1
    got = native._mosaic_morph_py(labels, 2)
    want = native.mosaic_morph_host(labels, 2)
    for g, x in zip(got, want):
        np.testing.assert_array_equal(g, x)
    area, cy, cx, ymin, ymax, xmin, xmax = got
    assert area[1] == 105 and ymax[1] == 2 and xmax[1] == w - 1


def _tiff_lzw_encode(data: bytes) -> bytes:
    """Full TIFF-LZW encoder: exists so the decoder's 10-12-bit widths,
    wide-width KwKwK, and table-cap paths have in-suite coverage — the
    round-trip fixtures written by cv2 never leave 9-bit codes.

    The code width used for each emission is decided by SIMULATING the
    decoder's state (its table lags the encoder's by one code, which is
    exactly what the TIFF early-change convention compensates for), so
    encoder and decoder agree by construction."""
    out = bytearray()
    acc = 0
    nbits = 0
    # decoder-side state the emitter mirrors
    dec_next = 258
    dec_width = 9
    dec_prev = False

    def emit_raw(code):
        nonlocal acc, nbits
        acc = (acc << dec_width) | code
        nbits += dec_width
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)

    def emit_data(code):
        nonlocal dec_next, dec_width, dec_prev
        emit_raw(code)
        # what our decoder does after consuming a data code
        if dec_prev and dec_next < 4096:
            dec_next += 1
            if dec_next + 1 >= (1 << dec_width) and dec_width < 12:
                dec_width += 1
        dec_prev = True

    def emit_clear():
        nonlocal dec_next, dec_width, dec_prev
        emit_raw(256)
        dec_next, dec_width, dec_prev = 258, 9, False

    def fresh_table():
        return {bytes([i]): i for i in range(256)}

    table = fresh_table()
    next_code = 258
    emit_clear()
    w = b""
    for byte in data:
        wc = w + bytes([byte])
        if wc in table:
            w = wc
            continue
        emit_data(table[w])
        table[wc] = next_code
        next_code += 1
        if next_code >= 4093:  # table nearly full: restart
            emit_clear()
            table = fresh_table()
            next_code = 258
        w = bytes([byte])
    if w:
        emit_data(table[w])
    emit_raw(257)  # EOI
    if nbits:
        out.append((acc << (8 - nbits)) & 0xFF)
    return bytes(out)


def test_lzw_full_width_round_trip(rng):
    """Native and Python LZW decoders on streams that grow the code
    width to 12 bits, hit the table cap (mid-stream Clear), and contain
    KwKwK chains — none of which the cv2-written fixtures exercise."""
    from tmlibrary_tpu import native

    random_part = bytes(rng.integers(0, 256, 30000, dtype=np.uint8))
    kwkwk_part = b"abababab" * 64 + bytes([7]) * 512
    for data in (
        random_part,                      # table cap + width 12 + Clear
        kwkwk_part,                       # KwKwK chains
        kwkwk_part + random_part,         # both, across a Clear
        b"",                              # empty stream
    ):
        encoded = _tiff_lzw_encode(data)
        got_native = native.lzw_decode(encoded, len(data))
        got_py = native._lzw_decode_py(encoded, len(data))
        assert got_native == data, f"native mismatch on {len(data)}-byte input"
        assert got_py == data, f"python twin mismatch on {len(data)}-byte input"

    # truncations of a wide-width stream must fail cleanly, never crash,
    # and native/python must agree
    encoded = _tiff_lzw_encode(random_part)
    for cut in (1, 100, len(encoded) // 2, len(encoded) - 2):
        n = native.lzw_decode(encoded[:cut], len(random_part))
        p = native._lzw_decode_py(encoded[:cut], len(random_part))
        assert n == p


def test_site_stats_kernels_bit_identical_to_xla(rng):
    """The round-5 measurement kernels (tm_site_stats, tm_hist_counts,
    tm_otsu_hist) promise BIT parity with their XLA twins — the dispatch
    swap must not be able to move a single feature value or threshold.
    Covers out-of-range labels (dropped like segment ids), negative
    histogram indices (jnp wraps once), and the Otsu span floor."""
    from tmlibrary_tpu import native
    from tmlibrary_tpu.ops.histogram import histogram_fixed_bins
    from tmlibrary_tpu.ops.measure import intensity_features
    from tmlibrary_tpu.ops.threshold import otsu_value

    if not native.has_site_stats():
        pytest.skip("native measurement kernels unavailable")
    import jax

    labels = rng.integers(0, 70, (3, 64, 64)).astype(np.int32)  # ids > 48
    img = rng.normal(500, 100, (3, 64, 64)).astype(np.float32)
    f_nat = jax.jit(jax.vmap(
        lambda l, i: intensity_features(l, i, 48, method="native")
    ))(labels, img)
    f_xla = jax.jit(jax.vmap(
        lambda l, i: intensity_features(l, i, 48, method="xla")
    ))(labels, img)
    for k in f_nat:
        np.testing.assert_array_equal(np.asarray(f_nat[k]), np.asarray(f_xla[k]))

    idx = rng.integers(-600, 600, (3, 64, 64)).astype(np.int32)
    h_nat = jax.jit(jax.vmap(
        lambda a: histogram_fixed_bins(a, 256, method="native")
    ))(idx)
    h_sca = jax.jit(jax.vmap(
        lambda a: histogram_fixed_bins(a, 256, method="scatter")
    ))(idx)
    np.testing.assert_array_equal(np.asarray(h_nat), np.asarray(h_sca))

    probes = [
        img,
        np.zeros((1, 8, 8), np.float32),           # span floor
        np.full((1, 8, 8), 7.25, np.float32),      # constant image
    ]
    for p in probes:
        a = jax.vmap(lambda x: otsu_value(x, method="native"))(p)
        b = jax.vmap(lambda x: otsu_value(x, method="xla"))(p)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # unbatched (no vmap) shape contract
    a = otsu_value(img[0], method="native")
    b = otsu_value(img[0], method="xla")
    assert np.asarray(a) == np.asarray(b)


def test_batched_callbacks_single_device_subprocess():
    """Under the suite's 8-virtual-device backend the measurement
    callbacks must pick the SPMD-safe ``sequential`` method (expand_dims
    deadlocks the partitioner's collective rendezvous — round-5 abort in
    test_determinism), while a single-device process gets the batched
    ``expand_dims`` fast path.  The subprocess runs WITHOUT the
    8-device flag to pin the fast path's correctness."""
    import os
    import subprocess
    import sys

    from tmlibrary_tpu import native as nat

    assert nat.callback_vmap_method() == "sequential"  # 8-device suite env
    if not nat.has_site_stats():
        pytest.skip("native measurement kernels unavailable")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tmlibrary_tpu import native
from tmlibrary_tpu.ops.measure import intensity_features
assert native.callback_vmap_method() == "expand_dims", jax.devices()
rng = np.random.default_rng(3)
labels = rng.integers(0, 20, (4, 32, 32)).astype(np.int32)
img = rng.normal(100, 10, (4, 32, 32)).astype(np.float32)
nat = jax.jit(jax.vmap(lambda l, i: intensity_features(l, i, 16, method="native")))(labels, img)
xla = jax.jit(jax.vmap(lambda l, i: intensity_features(l, i, 16, method="xla")))(labels, img)
for k in nat:
    np.testing.assert_array_equal(np.asarray(nat[k]), np.asarray(xla[k]))
print("OK")
"""
    r = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=300, cwd=str(pathlib.Path(__file__).parent.parent),
    )
    assert r.returncode == 0 and "OK" in r.stdout, r.stderr[-1500:]


def test_channel_sums_minmax_bit_identical_to_scatter(rng):
    """The multi-channel reduction kernels (tm_site_channel_sums /
    tm_site_channel_minmax) are bit-identical to the XLA segment
    scatters.  They are EXPLICIT opt-in (method="native") — auto-routing
    them hung XLA-CPU inside morphology's program (see grouped_sums) —
    but the kernels themselves stay correct and covered."""
    import jax
    import jax.numpy as jnp

    from tmlibrary_tpu import native as nat
    from tmlibrary_tpu.ops.measure import grouped_minmax_multi, grouped_sums

    if not nat.has_site_stats():
        pytest.skip("native measurement kernels unavailable")
    labels = rng.integers(0, 20, (3, 64, 64)).astype(np.int32)
    a = rng.normal(100, 10, (3, 64, 64)).astype(np.float32)
    b = rng.normal(5, 2, (3, 64, 64)).astype(np.float32)
    gs_n = jax.jit(jax.vmap(lambda l, x, y: grouped_sums(
        l, [jnp.ones_like(x), x, y], 16, method="native")))(labels, a, b)
    gs_s = jax.jit(jax.vmap(lambda l, x, y: grouped_sums(
        l, [jnp.ones_like(x), x, y], 16, method="scatter")))(labels, a, b)
    np.testing.assert_array_equal(np.asarray(gs_n), np.asarray(gs_s))
    mm_n = jax.jit(jax.vmap(lambda l, x, y: grouped_minmax_multi(
        l, [x, y], 16, method="native")))(labels, a, b)
    mm_s = jax.jit(jax.vmap(lambda l, x, y: grouped_minmax_multi(
        l, [x, y], 16, method="scatter")))(labels, a, b)
    for got, want in zip(mm_n, mm_s):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_site_glcm_bit_identical_to_scatter(rng):
    """tm_site_glcm (fused per-object quantization + 4-direction GLCMs)
    is bit-identical to the scatter path — GLCM counts are exact
    integers and the stretch replicates quantize_per_object's f32
    expression tree.  Explicit opt-in (see _resolve_glcm_method)."""
    import jax
    import jax.numpy as jnp

    from tmlibrary_tpu import native as nat
    from tmlibrary_tpu.ops.measure import haralick_features

    if not nat.has_site_glcm():
        pytest.skip("native GLCM kernel unavailable")
    labels = rng.integers(0, 70, (4, 96, 96)).astype(np.int32)  # ids > 48
    img = rng.normal(500, 100, (4, 96, 96)).astype(np.float32)
    f_nat = jax.jit(jax.vmap(lambda l, i: haralick_features(
        l, i, 48, levels=16, glcm_method="native")))(labels, img)
    f_sca = jax.jit(jax.vmap(lambda l, i: haralick_features(
        l, i, 48, levels=16, glcm_method="scatter")))(labels, img)
    for k in f_nat:
        np.testing.assert_array_equal(np.asarray(f_nat[k]), np.asarray(f_sca[k]))
