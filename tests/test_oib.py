"""Olympus FluoView ``.oif``/``.oib`` container support.

``.oif`` is a UTF-16 INI main file next to a ``.oif.files/`` directory of
single-plane TIFFs named by axis tokens; ``.oib`` packs the same tree
into one OLE2 compound document.  ``write_cfb`` below is a minimal CFB
v3 writer (FAT, directory tree, mini stream) so the first-party parser
(:mod:`tmlibrary_tpu.cfb`) is tested against synthetic fixtures — real
containers cannot be fetched in this environment.
"""
import struct

import numpy as np
import pytest

from tmlibrary_tpu.cfb import CompoundFile
from tmlibrary_tpu.errors import MetadataError
from tmlibrary_tpu.readers import OIBReader, OIFReader

SECT = 512
MINI = 64
FREE = 0xFFFFFFFF
END = 0xFFFFFFFE
FATSECT = 0xFFFFFFFD


# ------------------------------------------------------------ TIFF fixture
def _entry(tag, typ, count, value):
    return struct.pack("<HHII", tag, typ, count, value)


def tiff_bytes(plane: np.ndarray) -> bytes:
    """Minimal single-IFD little-endian grayscale TIFF."""
    h, w = plane.shape
    bits = plane.dtype.itemsize * 8
    data = np.ascontiguousarray(plane).tobytes()
    buf = bytearray(b"II*\x00\x00\x00\x00\x00")
    data_off = len(buf)
    buf += data
    entries = [
        _entry(256, 3, 1, w),
        _entry(257, 3, 1, h),
        _entry(258, 3, 1, bits),
        _entry(259, 3, 1, 1),
        _entry(262, 3, 1, 1),
        _entry(273, 4, 1, data_off),
        _entry(277, 3, 1, 1),
        _entry(278, 3, 1, h),
        _entry(279, 4, 1, len(data)),
    ]
    ifd_off = len(buf)
    buf += struct.pack("<H", len(entries)) + b"".join(entries)
    buf += b"\x00\x00\x00\x00"
    struct.pack_into("<I", buf, 4, ifd_off)
    return bytes(buf)


# ------------------------------------------------------------- CFB writer
def _pad(b: bytes, unit: int) -> bytes:
    rem = len(b) % unit
    return b + b"\x00" * (unit - rem) if rem else b


def write_cfb(files: "dict[str, bytes]", sect: int = SECT) -> bytes:
    """CFB container holding ``files`` ("Storage/Stream" paths allowed,
    one nesting level).  Streams < 4096 bytes land in the mini stream.
    ``sect``: 512 (v3, default) or 4096 (v4)."""
    assert sect in (512, 4096)
    per_fat = sect // 4
    # ---- directory tree -------------------------------------------------
    entries: list[dict] = [dict(
        name="Root Entry", type=5, left=FREE, right=FREE, child=FREE,
        start=END, size=0,
    )]
    storages: dict[str, int] = {}
    children: dict[int, list[int]] = {0: []}

    def add_entry(name, etype, parent) -> int:
        eid = len(entries)
        entries.append(dict(name=name, type=etype, left=FREE, right=FREE,
                            child=FREE, start=END, size=0))
        children.setdefault(eid, [])
        children[parent].append(eid)
        return eid

    stream_ids: dict[str, int] = {}
    for path in files:
        parent = 0
        parts = path.split("/")
        for storage in parts[:-1]:
            key = "/".join(parts[: parts.index(storage) + 1])
            if key not in storages:
                storages[key] = add_entry(storage, 1, parent)
            parent = storages[key]
        stream_ids[path] = add_entry(parts[-1], 2, parent)

    for parent, kids in children.items():
        if not kids:
            continue
        entries[parent]["child"] = kids[0]
        for a, b in zip(kids, kids[1:]):
            entries[a]["right"] = b

    # ---- payload placement ---------------------------------------------
    mini_payload = bytearray()
    minifat: list[int] = []
    large: list[tuple[str, bytes]] = []
    for path, payload in files.items():
        e = entries[stream_ids[path]]
        e["size"] = len(payload)
        if len(payload) < 4096:
            first = len(minifat)
            n = max(1, (len(payload) + MINI - 1) // MINI)
            for i in range(n):
                minifat.append(first + i + 1 if i < n - 1 else END)
            e["start"] = first
            mini_payload += _pad(payload, MINI)
        else:
            large.append((path, payload))

    dir_raw = bytearray()
    for e in entries:
        name = e["name"].encode("utf-16-le") + b"\x00\x00"
        ent = bytearray(128)
        ent[: len(name)] = name
        struct.pack_into("<H", ent, 64, len(name))
        ent[66] = e["type"]
        ent[67] = 1
        struct.pack_into("<3I", ent, 68, e["left"], e["right"], e["child"])
        struct.pack_into("<I", ent, 116, e["start"] & 0xFFFFFFFF)
        struct.pack_into("<Q", ent, 120, e["size"])
        dir_raw += ent
    n_dir = len(_pad(bytes(dir_raw), sect)) // sect

    minifat_raw = b"".join(struct.pack("<I", v) for v in minifat)
    n_minifat = len(_pad(minifat_raw, sect)) // sect if minifat else 0
    mini_raw = _pad(bytes(mini_payload), sect)
    n_mini = len(mini_raw) // sect
    n_large = [len(_pad(p, sect)) // sect for _, p in large]

    body = n_dir + n_minifat + n_mini + sum(n_large)
    n_fat = 1
    while (body + n_fat + per_fat - 1) // per_fat > n_fat:
        n_fat += 1
    total = body + n_fat

    # sector order: [FAT][dir][miniFAT][ministream][large...]
    fat = [FREE] * (n_fat * per_fat)
    nxt = 0
    for i in range(n_fat):
        fat[nxt] = FATSECT
        nxt += 1

    def place(n_sectors) -> int:
        nonlocal nxt
        start = nxt
        for i in range(n_sectors):
            fat[nxt] = nxt + 1 if i < n_sectors - 1 else END
            nxt += 1
        return start

    dir_start = place(n_dir)
    minifat_start = place(n_minifat) if n_minifat else END
    mini_start = place(n_mini) if n_mini else END
    for (path, payload), n in zip(large, n_large):
        entries[stream_ids[path]]["start"] = place(n)
    if mini_payload:
        entries[0]["start"] = mini_start
        entries[0]["size"] = len(mini_payload)

    # directory raw must be rebuilt: large-stream starts were just placed
    dir_raw = bytearray()
    for e in entries:
        name = e["name"].encode("utf-16-le") + b"\x00\x00"
        ent = bytearray(128)
        ent[: len(name)] = name
        struct.pack_into("<H", ent, 64, len(name))
        ent[66] = e["type"]
        ent[67] = 1
        struct.pack_into("<3I", ent, 68, e["left"], e["right"], e["child"])
        struct.pack_into("<I", ent, 116, e["start"] & 0xFFFFFFFF)
        struct.pack_into("<Q", ent, 120, e["size"])
        dir_raw += ent

    header = bytearray(sect)  # v3: header == one 512-byte sector; v4: padded
    header[:8] = b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1"
    struct.pack_into("<H", header, 24, 0x3E)
    struct.pack_into("<H", header, 26, 3 if sect == 512 else 4)
    struct.pack_into("<H", header, 28, 0xFFFE)
    struct.pack_into("<H", header, 30, 9 if sect == 512 else 12)
    struct.pack_into("<H", header, 32, 6)
    struct.pack_into("<I", header, 44, n_fat)
    struct.pack_into("<I", header, 48, dir_start)
    struct.pack_into("<I", header, 56, 4096)
    struct.pack_into("<I", header, 60, minifat_start)
    struct.pack_into("<I", header, 64, n_minifat)
    struct.pack_into("<I", header, 68, END)
    struct.pack_into("<I", header, 72, 0)
    for i in range(109):
        struct.pack_into("<I", header, 76 + 4 * i,
                         i if i < n_fat else FREE)

    out = bytearray(header)
    out += b"".join(struct.pack("<I", v) for v in fat)
    out += _pad(bytes(dir_raw), sect)
    if n_minifat:
        out += _pad(minifat_raw, sect)
    out += mini_raw
    for (_, payload), n in zip(large, n_large):
        out += _pad(payload, sect)
    assert len(out) == sect + total * sect
    return bytes(out)


# ------------------------------------------------------------ OIF fixture
def oif_text(w, h, c, z, t) -> str:
    lines = ["[Version Info]", 'SystemName="FLUOVIEW FV1000"']
    for i, (code, size) in enumerate(
        (("X", w), ("Y", h), ("C", c), ("Z", z), ("T", t))
    ):
        lines += [
            f"[Axis {i} Parameters Common]",
            f'AxisCode="{code}"',
            f"MaxSize={size}",
        ]
    return "\r\n".join(lines) + "\r\n"


def plane_name(c, z, t) -> str:
    return f"s_C{c + 1:03d}Z{z + 1:03d}T{t + 1:03d}.tif"


def write_oif(dirpath, stem, stack: np.ndarray):
    """``stack``: (C, Z, T, H, W) uint16 -> ``<stem>.oif`` + files dir."""
    n_c, n_z, n_t, h, w = stack.shape
    main = dirpath / f"{stem}.oif"
    main.write_bytes(
        b"\xff\xfe"
        + oif_text(w, h, n_c, n_z, n_t).encode("utf-16-le")
    )
    files = dirpath / f"{stem}.oif.files"
    files.mkdir()
    for c in range(n_c):
        for z in range(n_z):
            for t in range(n_t):
                (files / plane_name(c, z, t)).write_bytes(
                    tiff_bytes(stack[c, z, t])
                )
    return main


def write_oib(path, stack: np.ndarray, with_info=True, nested=True):
    """``stack``: (C, Z, T, H, W) -> OIB compound file."""
    n_c, n_z, n_t, h, w = stack.shape
    prefix = "Storage00001/" if nested else ""
    files: dict[str, bytes] = {}
    info_lines = ["[OibSaveInfo]", 'Version="2.0.0.0"']
    idx = 0
    for c in range(n_c):
        for z in range(n_z):
            for t in range(n_t):
                stream = f"Stream{idx:05d}" if with_info else plane_name(c, z, t)
                files[prefix + stream] = tiff_bytes(stack[c, z, t])
                if with_info:
                    info_lines.append(f"{stream}={plane_name(c, z, t)}")
                idx += 1
    main_stream = f"Stream{idx:05d}" if with_info else "main.oif"
    files[prefix + main_stream] = (
        b"\xff\xfe"
        + oif_text(w, h, n_c, n_z, n_t).encode("utf-16-le")
    )
    if with_info:
        info_lines.append(f"{main_stream}=main.oif")
        files["OibInfo.txt"] = (
            b"\xff\xfe"
            + "\r\n".join(info_lines).encode("utf-16-le")
        )
    path.write_bytes(write_cfb(files))
    return path


@pytest.fixture()
def stack():
    rng = np.random.default_rng(23)
    return rng.integers(0, 60000, (2, 3, 2, 16, 20), dtype=np.uint16)


# ------------------------------------------------------------------ tests
def test_cfb_roundtrip_mini_and_large():
    small = b"hello mini stream"
    big = bytes(np.arange(5000, dtype=np.uint8) % 251)
    blob = write_cfb({"Small.txt": small, "Dir01/Big.bin": big})
    cf = CompoundFile(blob)
    assert cf.streams["Small.txt"] == small
    assert cf.streams["Dir01/Big.bin"] == big


def test_cfb_rejects_corruption(tmp_path):
    with pytest.raises(MetadataError):
        CompoundFile(b"\x00" * 600)
    blob = write_cfb({"a.txt": b"x" * 100})
    with pytest.raises(MetadataError):
        CompoundFile(blob[:512])  # FAT/directory sectors cut off
    # directory start pointing into the void
    bad = bytearray(blob)
    struct.pack_into("<I", bad, 48, 10_000)
    with pytest.raises(MetadataError):
        CompoundFile(bytes(bad))


def test_oif_reader_dims_and_planes(tmp_path, stack):
    main = write_oif(tmp_path, "exp_A01", stack)
    with OIFReader(main) as r:
        assert (r.n_channels, r.n_zplanes, r.n_tpoints) == (2, 3, 2)
        assert (r.height, r.width) == (16, 20)
        for c in range(2):
            for z in range(3):
                for t in range(2):
                    np.testing.assert_array_equal(
                        r.read_plane(c, z, t), stack[c, z, t]
                    )
        page = (1 * 3 + 2) * 2 + 1  # (c*Z + z)*T + t
        np.testing.assert_array_equal(
            r.read_plane_linear(page), stack[1, 2, 1]
        )


@pytest.mark.parametrize("with_info,nested", [(True, True), (False, False)])
def test_oib_reader(tmp_path, stack, with_info, nested):
    path = write_oib(tmp_path / "exp.oib", stack, with_info, nested)
    with OIBReader(path) as r:
        assert (r.n_channels, r.n_zplanes, r.n_tpoints) == (2, 3, 2)
        assert (r.height, r.width) == (16, 20)
        np.testing.assert_array_equal(r.read_plane(1, 2, 1), stack[1, 2, 1])
        np.testing.assert_array_equal(
            r.read_plane_linear((0 * 3 + 1) * 2 + 0), stack[0, 1, 0]
        )


def test_oif_rejects_bad_files(tmp_path, stack):
    missing_dir = tmp_path / "lonely.oif"
    missing_dir.write_bytes(oif_text(8, 8, 1, 1, 1).encode("utf-16"))
    with pytest.raises(MetadataError):
        OIFReader(missing_dir).__enter__()
    not_oif = tmp_path / "junk.oif"
    not_oif.write_bytes(b"random bytes, no ini")
    with pytest.raises(MetadataError):
        OIFReader(not_oif).__enter__()
    not_cfb = tmp_path / "junk.oib"
    not_cfb.write_bytes(b"\x01" * 4096)
    with pytest.raises(MetadataError):
        OIBReader(not_cfb).__enter__()


def test_olympus_ingest_end_to_end(tmp_path, stack):
    """Mixed .oif/.oib wells -> metaconfig (auto) -> imextract -> pixels
    in the canonical store, bit-identical, Z/T preserved."""
    from tmlibrary_tpu.models.experiment import Experiment
    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.workflow.registry import get_step

    rng = np.random.default_rng(31)
    src = tmp_path / "source"
    src.mkdir()
    data = {
        "A01": rng.integers(0, 60000, (2, 3, 2, 16, 20), dtype=np.uint16),
        "B02": rng.integers(0, 60000, (2, 3, 2, 16, 20), dtype=np.uint16),
    }
    write_oif(src, "exp_A01", data["A01"])
    write_oib(src / "exp_B02.oib", data["B02"])

    root = tmp_path / "exp"
    store = ExperimentStore.create(
        root, Experiment(name="oibtest", plates=[], channels=[],
                         site_height=1, site_width=1))
    meta = get_step("metaconfig")(store)
    meta.init({"source_dir": str(src), "handler": "auto"})
    result = meta.run(0)
    assert result["n_files"] == 2 * 2 * 3 * 2  # wells x C x Z x T

    exp = ExperimentStore.open(root).experiment
    assert exp.n_zplanes == 3 and exp.n_tpoints == 2
    rows_cols = {(w.row, w.column) for p in exp.plates for w in p.wells}
    assert rows_cols == {(0, 0), (1, 1)}

    ime = get_step("imextract")(store)
    ime.init({})
    for j in ime.list_batches():
        ime.run(j)

    store = ExperimentStore.open(root)
    for c in range(2):
        for z in range(3):
            for t in range(2):
                px = store.read_sites(None, channel=c, tpoint=t, zplane=z)
                np.testing.assert_array_equal(px[0], data["A01"][c, z, t])
                np.testing.assert_array_equal(px[1], data["B02"][c, z, t])


def test_olympus_handler_skips_unreadable(tmp_path, stack):
    from tmlibrary_tpu.workflow.steps.vendors import olympus_sidecar

    src = tmp_path / "source"
    src.mkdir()
    write_oif(src, "ok_A01", stack)
    (src / "bad_B01.oib").write_bytes(b"\0" * 2048)
    entries, skipped = olympus_sidecar(src)
    assert skipped == 1
    assert {e["well_row"] for e in entries} == {0}
    assert len(entries) == 2 * 3 * 2


def test_oif_aborted_scan_trims_trailing_timepoint(tmp_path, stack):
    """INI declares T=2 but the last timepoint is partial (aborted scan):
    the reader trims to the complete timepoints instead of failing every
    missing (c,z,t) at extract time."""
    main = write_oif(tmp_path, "abort_A01", stack)
    files = tmp_path / "abort_A01.oif.files"
    # drop most of t=1 (keep one plane so t=1 is observed but incomplete)
    for c in range(2):
        for z in range(3):
            if (c, z) != (0, 0):
                (files / plane_name(c, z, 1)).unlink()
    with OIFReader(main) as r:
        assert r.n_tpoints == 1
        assert (r.n_channels, r.n_zplanes) == (2, 3)
        np.testing.assert_array_equal(r.read_plane(1, 2, 0), stack[1, 2, 0])


def test_oif_rejects_mid_grid_hole(tmp_path, stack):
    main = write_oif(tmp_path, "holey_A01", stack)
    (tmp_path / "holey_A01.oif.files" / plane_name(0, 1, 0)).unlink()
    with pytest.raises(MetadataError, match="incomplete"):
        OIFReader(main).__enter__()


def test_oib_duplicate_basename_first_storage_wins(tmp_path):
    """A later storage's duplicate copy of a plane (preview exports) must
    not shadow the acquisition plane in the first storage."""
    rng = np.random.default_rng(5)
    real = rng.integers(0, 60000, (8, 9), dtype=np.uint16)
    preview = np.zeros((8, 9), np.uint16)
    name = plane_name(0, 0, 0)
    blob = write_cfb({
        f"Storage00001/{name}": tiff_bytes(real),
        f"Storage00002/{name}": tiff_bytes(preview),
    })
    path = tmp_path / "dup.oib"
    path.write_bytes(blob)
    with OIBReader(path) as r:
        np.testing.assert_array_equal(r.read_plane(0, 0, 0), real)


def test_oib_per_storage_oibinfo_sections(tmp_path):
    """OibInfo.txt grouped in per-storage sections: equal stream
    basenames in different storages map to DIFFERENT plane names."""
    rng = np.random.default_rng(9)
    p0 = rng.integers(0, 60000, (6, 7), dtype=np.uint16)
    p1 = rng.integers(0, 60000, (6, 7), dtype=np.uint16)
    info = "\r\n".join([
        "[Storage00001]",
        f"Stream00000={plane_name(0, 0, 0)}",
        "[Storage00002]",
        f"Stream00000={plane_name(1, 0, 0)}",
        "[General]",
        "Stream00099=main.oif",
    ])
    blob = write_cfb({
        "OibInfo.txt": b"\xff\xfe" + info.encode("utf-16-le"),
        "Storage00001/Stream00000": tiff_bytes(p0),
        "Storage00002/Stream00000": tiff_bytes(p1),
        "Stream00099": b"\xff\xfe"
        + oif_text(7, 6, 2, 1, 1).encode("utf-16-le"),
    })
    path = tmp_path / "sections.oib"
    path.write_bytes(blob)
    with OIBReader(path) as r:
        assert r.n_channels == 2
        np.testing.assert_array_equal(r.read_plane(0, 0, 0), p0)
        np.testing.assert_array_equal(r.read_plane(1, 0, 0), p1)


def test_cfb_lazy_stream_api():
    blob = write_cfb({"A/x.bin": b"1" * 5000, "y.txt": b"hi"})
    cf = CompoundFile(blob)
    assert set(cf.stream_paths) == {"A/x.bin", "y.txt"}
    assert cf.read_stream("y.txt") == b"hi"
    with pytest.raises(MetadataError):
        cf.read_stream("missing")


def test_cfb_v4_4096_byte_sectors(tmp_path):
    """Version-4 compound files (4096-byte sectors) parse identically —
    the OIB path is v3 in practice but the parser claims both."""
    small = b"mini stream payload"
    big = bytes(np.arange(9000, dtype=np.uint8) % 253)
    blob = write_cfb({"S/big.bin": big, "small.txt": small}, sect=4096)
    cf = CompoundFile(blob)
    assert cf.read_stream("small.txt") == small
    assert cf.read_stream("S/big.bin") == big

    rng = np.random.default_rng(51)
    stack = rng.integers(0, 60000, (1, 2, 1, 8, 9), dtype=np.uint16)
    # an OIB written as v4 still reads end-to-end
    prefix = "Storage00001/"
    files = {
        prefix + plane_name(0, z, 0): tiff_bytes(stack[0, z, 0])
        for z in range(2)
    }
    files[prefix + "main.oif"] = b"\xff\xfe" + oif_text(
        9, 8, 1, 2, 1
    ).encode("utf-16-le")
    path = tmp_path / "v4.oib"
    path.write_bytes(write_cfb(files, sect=4096))
    with OIBReader(path) as r:
        assert (r.n_channels, r.n_zplanes, r.n_tpoints) == (1, 2, 1)
        np.testing.assert_array_equal(r.read_plane(0, 1, 0), stack[0, 1, 0])


def test_olympus_channel_names_from_dye_sections(tmp_path, stack):
    """[Channel N Parameters] DyeName labels the ingest channels."""
    extra = "\r\n".join([
        "[Channel 1 Parameters]", 'DyeName="DAPI"',
        "[Channel 2 Parameters]", 'DyeName="Alexa 568"',
    ])
    main = write_oif(tmp_path, "dyes_A01", stack)
    main.write_bytes(
        main.read_bytes() + ("\r\n" + extra).encode("utf-16-le")
    )
    with OIFReader(main) as r:
        assert r.channel_names == ["DAPI", "Alexa 568"]

    from tmlibrary_tpu.workflow.steps.vendors import olympus_sidecar

    entries, _ = olympus_sidecar(tmp_path)
    # order-sensitive: channel index c must carry labels[c] (a set
    # comparison could not catch a label/index misalignment)
    by_page = {e["page"]: e["channel"] for e in entries}
    n_z, n_t = 3, 2
    for c, label in enumerate(["DAPI", "Alexa-568"]):
        for z in range(n_z):
            for t in range(n_t):
                assert by_page[(c * n_z + z) * n_t + t] == label

