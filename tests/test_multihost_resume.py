"""REAL host-death resume test: a subprocess worker is hard-killed
(``os._exit``, via the fault harness's ``kill`` kind) in the middle of
the jterator step, then a second subprocess resumes from the on-disk
run ledger.  Every prior chaos test injected *exceptions* into one
process — catchable, unwindable, ``finally``-visible.  A preempted TPU
VM offers none of that: the ledger's crash-durability and the resume
replay are the only recovery surface, and this test crosses a real
process boundary to prove they suffice.

Convergence bar: the killed-then-resumed store must match a fault-free
reference run bit for bit — same label stacks, same feature tables —
and the resume must not redo work the ledger already recorded.
"""
import json
import os
import subprocess
import sys

import numpy as np

from test_pipelined import _read_features_sorted  # noqa: F401
from test_workflow import (  # noqa: F401 — fixture re-export
    make_description,
    source_dir,
    store,
    synth_site_image,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_resume_worker.py")


def _launch(store_root, desc_path, phase, extra_env=None):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("TMX_FAULT_PLAN", None)  # never inherit a plan by accident
    if extra_env:
        env.update(extra_env)
    return subprocess.run(
        [sys.executable, WORKER, str(store_root), str(desc_path), phase],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=240,
    )


def test_killed_worker_resume_converges(tmp_path, source_dir, store):
    import pandas.testing

    from tmlibrary_tpu.models.experiment import Experiment
    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.workflow.engine import RunLedger, Workflow

    desc = make_description(source_dir, store)
    desc_path = store.root / "workflow.yaml"
    desc.save(desc_path)

    # ---- phase 1: worker dies mid-step (kill = os._exit, rc 41) ----
    plan = {"faults": [{"site": "batch_run", "step": "jterator",
                        "batch": 1, "kind": "kill"}]}
    plan_file = tmp_path / "plan.json"
    plan_file.write_text(json.dumps(plan))
    p1 = _launch(store.root, desc_path, "run",
                 {"TMX_FAULT_PLAN": str(plan_file)})
    assert p1.returncode == 41, \
        f"expected injected host death, got rc {p1.returncode}:\n" \
        f"{p1.stdout[-3000:]}"
    assert "WORKER_DONE" not in p1.stdout

    # the ledger survived the death mid-step: prep steps done, jterator
    # batch 0 recorded, batch 1 and step_done missing
    ledger = RunLedger(store.workflow_dir / "ledger.jsonl")
    assert {"metaconfig", "imextract", "corilla"} <= \
        ledger.completed_steps()
    assert "jterator" not in ledger.completed_steps()
    assert ledger.completed_batches("jterator") == {0}

    # ---- phase 2: a fresh process resumes from the ledger alone ----
    p2 = _launch(store.root, desc_path, "resume")
    assert p2.returncode == 0, f"resume failed:\n{p2.stdout[-3000:]}"
    assert "WORKER_DONE phase=resume" in p2.stdout

    ledger = RunLedger(store.workflow_dir / "ledger.jsonl")
    assert "jterator" in ledger.completed_steps()
    assert ledger.completed_batches("jterator") == {0, 1}
    # resume did NOT redo batch 0 — one batch_done per batch across both
    # processes' appends
    done = [e["batch"] for e in ledger.events()
            if e.get("event") == "batch_done"
            and e.get("step") == "jterator"]
    assert sorted(done) == [0, 1]
    # both the killed run and the resume stamped run_started; the resume
    # flagged itself
    starts = [e for e in ledger.events() if e.get("event") == "run_started"]
    assert [s.get("resume") for s in starts] == [False, True]

    # ---- convergence: identical to a never-faulted reference run ----
    ref_store = ExperimentStore.create(
        tmp_path / "ref_exp",
        Experiment(name="wf", plates=[], channels=[], site_height=1,
                   site_width=1),
    )
    ref_desc = make_description(source_dir, ref_store)
    Workflow(ref_store, ref_desc).run()

    # reopen: metaconfig rewrote the manifest in the worker processes,
    # and the parent's in-memory store predates it
    resumed = ExperimentStore.open(store.root)
    assert np.array_equal(resumed.read_labels(None, "nuclei"),
                          ref_store.read_labels(None, "nuclei"))
    pandas.testing.assert_frame_equal(
        _read_features_sorted(resumed, "nuclei"),
        _read_features_sorted(ref_store, "nuclei"),
    )
