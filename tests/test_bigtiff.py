"""BigTIFF (magic 43) and deflate-strip decoding.

The first-party chain for plain TIFF pages is native C++ (classic,
none/LZW/PackBits) -> ``read_tiff_page_py`` (BigTIFF, deflate) -> cv2.
The writer here emits minimal single-strip-per-page files in both the
classic and BigTIFF layouts so the Python fallback is exercised without
any third-party encoder.
"""
import struct
import zlib

import numpy as np
import pytest

from tmlibrary_tpu.readers import ImageReader, read_tiff_page_py


def _entry(bo, big, tag, typ, vals, fmt):
    """One IFD entry with the value(s) packed inline, left-justified in
    the 4/8-byte value field (the TIFF rule for both byte orders)."""
    cap = 8 if big else 4
    packed = struct.pack(bo + fmt * len(vals), *vals)
    assert len(packed) <= cap, "inline-only writer"
    head = struct.pack(bo + ("HHQ" if big else "HHI"), tag, typ, len(vals))
    return head + packed.ljust(cap, b"\x00")


def write_tiff(path, planes, big=True, compression=1, predictor=1, bo="<"):
    """``planes``: (n, h, w) uint8/uint16; one strip per page."""
    n, h, w = planes.shape
    bits = planes.dtype.itemsize * 8
    order = b"II" if bo == "<" else b"MM"
    if big:
        buf = bytearray(struct.pack(bo + "2sHHHQ", order, 43, 8, 0, 0))
        first_ifd_at, off_fmt = 8, "Q"
    else:
        buf = bytearray(struct.pack(bo + "2sHI", order, 42, 0))
        first_ifd_at, off_fmt = 4, "I"

    strips = []
    for p in range(n):
        plane = np.ascontiguousarray(planes[p], dtype=bo + (
            "u1" if bits == 8 else "u2"))
        if predictor == 2:
            plane = np.concatenate(
                [plane[:, :1], np.diff(plane.astype(np.int64), axis=1)],
                axis=1,
            ).astype(plane.dtype)
        raw = plane.tobytes()
        if compression in (8, 32946):
            raw = zlib.compress(raw)
        elif compression != 1:
            raise AssertionError("writer supports none/deflate only")
        strips.append((len(buf), len(raw)))
        buf += raw

    ifd_offs, next_ptr_pos = [], []
    for p in range(n):
        entries = [
            _entry(bo, big, 256, 3, [w], "H"),
            _entry(bo, big, 257, 3, [h], "H"),
            _entry(bo, big, 258, 3, [bits], "H"),
            _entry(bo, big, 259, 3, [compression], "H"),
            _entry(bo, big, 262, 3, [1], "H"),
            _entry(bo, big, 273, 16 if big else 4, [strips[p][0]],
                   "Q" if big else "I"),
            _entry(bo, big, 277, 3, [1], "H"),
            _entry(bo, big, 278, 3, [h], "H"),
            _entry(bo, big, 279, 16 if big else 4, [strips[p][1]],
                   "Q" if big else "I"),
        ]
        if predictor != 1:
            entries.append(_entry(bo, big, 317, 3, [predictor], "H"))
        entries.sort(key=lambda e: struct.unpack_from(bo + "H", e)[0])
        ifd_offs.append(len(buf))
        buf += struct.pack(bo + ("Q" if big else "H"), len(entries))
        buf += b"".join(entries)
        next_ptr_pos.append(len(buf))
        buf += struct.pack(bo + off_fmt, 0)
    struct.pack_into(bo + off_fmt, buf, first_ifd_at, ifd_offs[0])
    for p in range(n - 1):
        struct.pack_into(bo + off_fmt, buf, next_ptr_pos[p], ifd_offs[p + 1])
    path.write_bytes(bytes(buf))
    return path


@pytest.fixture()
def planes():
    rng = np.random.default_rng(57)
    return rng.integers(0, 60000, (3, 10, 13), dtype=np.uint16)


@pytest.mark.parametrize("bo", ["<", ">"])
def test_bigtiff_pages_round_trip(tmp_path, planes, bo):
    path = write_tiff(tmp_path / "big.tif", planes, big=True, bo=bo)
    for p in range(3):
        np.testing.assert_array_equal(read_tiff_page_py(path, p), planes[p])
    assert read_tiff_page_py(path, 3) is None  # out of range -> cv2's turn


@pytest.mark.parametrize("big", [False, True])
@pytest.mark.parametrize("compression", [8, 32946])
def test_deflate_strips_round_trip(tmp_path, planes, big, compression):
    path = write_tiff(tmp_path / "z.tif", planes, big=big,
                      compression=compression)
    for p in range(3):
        np.testing.assert_array_equal(read_tiff_page_py(path, p), planes[p])


def test_deflate_with_horizontal_predictor(tmp_path, planes):
    path = write_tiff(tmp_path / "pred.tif", planes, big=True,
                      compression=8, predictor=2)
    np.testing.assert_array_equal(read_tiff_page_py(path, 1), planes[1])


def test_bigtiff_uint8(tmp_path):
    rng = np.random.default_rng(58)
    planes8 = rng.integers(0, 255, (2, 7, 9), dtype=np.uint8)
    path = write_tiff(tmp_path / "b8.tif", planes8, big=True, compression=8)
    out = read_tiff_page_py(path, 1)
    assert out.dtype == np.uint8
    np.testing.assert_array_equal(out, planes8[1])


def test_image_reader_falls_through_to_bigtiff(tmp_path, planes):
    """The public ImageReader boundary: native C++ declines magic 43,
    the Python fallback decodes it first-party (no cv2)."""
    path = write_tiff(tmp_path / "big.tif", planes, big=True, compression=8)
    with ImageReader(path) as r:
        np.testing.assert_array_equal(r.read(2), planes[2])


def test_imextract_read_plane_decodes_bigtiff(tmp_path, planes):
    from tmlibrary_tpu.workflow.steps.imextract import ImageExtractor

    path = write_tiff(tmp_path / "big.tif", planes, big=True)
    out = ImageExtractor._read_plane(str(path), 1, *planes.shape[1:])
    np.testing.assert_array_equal(out, planes[1])


def test_rgb_and_tiled_fall_through(tmp_path, planes):
    """A file the fallback can't model returns None (cv2's turn), it
    never guesses."""
    path = write_tiff(tmp_path / "big.tif", planes, big=True)
    buf = bytearray(path.read_bytes())
    # patch SamplesPerPixel (tag 277) of IFD 0 to 3
    (ifd0,) = struct.unpack_from("<Q", buf, 8)
    (n,) = struct.unpack_from("<Q", buf, ifd0)
    for i in range(n):
        p = ifd0 + 8 + 20 * i
        if struct.unpack_from("<H", buf, p)[0] == 277:
            struct.pack_into("<H", buf, p + 12, 3)
    path.write_bytes(bytes(buf))
    assert read_tiff_page_py(path, 0) is None


def test_parse_cache_detects_same_size_in_place_rewrite(tmp_path, planes):
    """A same-size rewrite inside one mtime tick must not serve a stale
    IFD parse: the validation key crcs the header plus EVERY walked IFD
    table span (wherever it sits in the file — mid-file IFDs included,
    round-4 advisor), so any parse-relevant byte change invalidates.
    The mtime is pinned across the rewrite to force the crc path."""

    def _entry_value_pos(buf, ifd_off, tag):
        (n,) = struct.unpack_from("<Q", buf, ifd_off)
        for i in range(n):
            p = ifd_off + 8 + 20 * i
            if struct.unpack_from("<H", buf, p)[0] == tag:
                return p + 12
        raise AssertionError(f"tag {tag} missing")

    import os

    path = write_tiff(tmp_path / "c.tif", planes, big=True)
    np.testing.assert_array_equal(read_tiff_page_py(path, 0), planes[0])
    st = os.stat(path)

    buf = bytearray(path.read_bytes())
    (ifd0,) = struct.unpack_from("<Q", buf, 8)
    (n,) = struct.unpack_from("<Q", buf, ifd0)
    (ifd1,) = struct.unpack_from("<Q", buf, ifd0 + 8 + 20 * n)
    v0 = _entry_value_pos(buf, ifd0, 273)
    v1 = _entry_value_pos(buf, ifd1, 273)
    (o0,) = struct.unpack_from("<Q", buf, v0)
    (o1,) = struct.unpack_from("<Q", buf, v1)
    struct.pack_into("<Q", buf, v0, o1)
    struct.pack_into("<Q", buf, v1, o0)
    path.write_bytes(bytes(buf))  # same size …
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns))  # … same mtime
    np.testing.assert_array_equal(read_tiff_page_py(path, 0), planes[1])


def test_fuzz_bigtiff_page_fallback(tmp_path, planes):
    """read_tiff_page_py's contract is narrower than the readers': it
    returns None (or a decoded array) on ANY input, never raises — a
    leak here would crash ingest's plain-TIFF path."""
    valid = write_tiff(tmp_path / "v.tif", planes, big=True,
                       compression=8).read_bytes()
    rng = np.random.default_rng(59)
    target = tmp_path / "m.tif"
    for _ in range(60):
        mutated = bytearray(valid)
        mutated[int(rng.integers(0, len(valid)))] ^= int(
            rng.integers(1, 256))
        target.write_bytes(bytes(mutated))
        for page in range(3):
            read_tiff_page_py(target, page)
    for _ in range(20):
        target.write_bytes(valid[:int(rng.integers(1, len(valid)))])
        for page in range(3):
            read_tiff_page_py(target, page)
