"""The per-config pipelined sweep (``bench.py --sweep``): strategy x depth
rows timed through the production PipelinedExecutor, the verdict merged
into TUNING.json (``config_sweeps`` + the per-backend
``reduction_strategy`` entry the "auto" resolver consumes), one summary
JSON line on stdout."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_sweep(env, timeout=420):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--sweep", "--child", "cpu"],
        env={**os.environ, "JAX_PLATFORMS": "cpu", **env},
        capture_output=True, text=True, timeout=timeout,
    )
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert lines, f"no JSON line: rc={proc.returncode} err={proc.stderr[-600:]}"
    return json.loads(lines[-1])


def test_sweep_grid_and_tuning_verdict(tmp_path):
    """Config 3 on CPU: every (strategy, depth) cell gets a row, and the
    winning cell's verdict lands in TUNING.json where
    ``tuned_reduction_strategy`` finds it — the acceptance pin for the
    sweep half of the strategy layer."""
    tuning = tmp_path / "TUNING.json"
    out = _run_sweep({
        "BENCH_CONFIG": "3",
        "BENCH_SITE_SIZE": "64",
        "BENCH_BATCH": "4",
        "BENCH_MAX_OBJECTS": "16",
        "BENCH_SWEEP_DEPTHS": "1,2",
        "BENCH_REPS": "1",
        "TMX_TUNING_JSON": str(tuning),
    })
    assert out["sweep"] is True
    assert out["config"] == "3"
    assert out["backend"] == "cpu"
    from tmlibrary_tpu.ops.reduction import STRATEGIES

    cells = {(r["strategy"], r["pipeline_depth"]) for r in out["rows"]}
    assert cells == {(s, d) for s in STRATEGIES for d in (1, 2)}
    assert all(r["items_per_sec"] > 0 for r in out["rows"])
    # every strategy-bearing row carries its on-chip working-set estimate
    assert all(r["vmem_bytes_estimate"] > 0 for r in out["rows"])
    assert out["best_strategy"] in STRATEGIES
    assert out["best_pipeline"] in (1, 2)

    doc = json.loads(tuning.read_text())
    assert doc["written_by"] == "bench.py --sweep"
    sweep = doc["config_sweeps"]["3"]
    assert sweep["best_strategy"] == out["best_strategy"]
    assert len(sweep["rows"]) == 2 * len(STRATEGIES)
    # the strategy axis is part of the methodology identity (the
    # regression sentinel must never compare a fused-bearing grid
    # against a pre-fused one)
    assert "strategies=" + "+".join(STRATEGIES) in sweep["timing_methodology"]
    assert doc["reduction_strategy"] == {"cpu": out["best_strategy"]}

    # the runtime resolver consumes exactly what the sweep wrote
    from tmlibrary_tpu.tuning import tuned_reduction_strategy

    os.environ["TMX_TUNING_JSON"] = str(tuning)
    try:
        assert tuned_reduction_strategy("cpu") == out["best_strategy"]
        assert tuned_reduction_strategy("tpu") is None
    finally:
        del os.environ["TMX_TUNING_JSON"]


def test_sweep_strategy_invariant_config(tmp_path):
    """corilla's chain has no grouped reductions: one strategy column
    (marked invariant), depth still swept, and NO reduction_strategy
    verdict written — sweeping noise must not set a tuned default."""
    tuning = tmp_path / "TUNING.json"
    out = _run_sweep({
        "BENCH_CONFIG": "corilla",
        "BENCH_SITE_SIZE": "32",
        "BENCH_SITES": "8",
        "BENCH_CHANNELS": "2",
        "BENCH_SWEEP_DEPTHS": "1,2",
        "BENCH_REPS": "1",
        "TMX_TUNING_JSON": str(tuning),
    })
    assert out["best_strategy"] is None
    assert [r["pipeline_depth"] for r in out["rows"]] == [1, 2]
    assert all(r.get("strategy_invariant") for r in out["rows"])
    doc = json.loads(tuning.read_text())
    assert "reduction_strategy" not in doc
    assert doc["config_sweeps"]["corilla"]["best_strategy"] is None


def test_sweep_preserves_tune_tpu_provenance(tmp_path):
    """A sweep merging into a file tune_tpu.py wrote must keep the
    hardware sweep's authorship and verdicts."""
    tuning = tmp_path / "TUNING.json"
    tuning.write_text(json.dumps({
        "written_by": "scripts/tune_tpu.py write_results",
        "best_batch": 128, "best_pipeline": 16,
        "timing_methodology": "pipelined-depth8",
    }))
    _run_sweep({
        "BENCH_CONFIG": "2",
        "BENCH_SITE_SIZE": "64",
        "BENCH_BATCH": "4",
        "BENCH_SWEEP_DEPTHS": "1",
        "BENCH_REPS": "1",
        "TMX_TUNING_JSON": str(tuning),
    })
    doc = json.loads(tuning.read_text())
    assert doc["written_by"] == "scripts/tune_tpu.py write_results"
    assert doc["best_batch"] == 128
    assert doc["best_pipeline"] == 16
    assert "2" in doc["config_sweeps"]


def test_sweep_rejects_unknown_strategy(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--sweep", "--child", "cpu"],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "BENCH_CONFIG": "3",
             "BENCH_SWEEP_STRATEGIES": "quantum",
             "TMX_TUNING_JSON": str(tmp_path / "TUNING.json")},
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode != 0
    assert "quantum" in proc.stderr
