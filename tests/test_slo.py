"""Per-tenant SLO accounting (``tmlibrary_tpu/slo.py``, ``tmx slo``).

The hand-computed fixture pins the burn math to the numbers documented in
DESIGN.md §21 (availability burn = bad-fraction over error budget,
latency burn = slow-fraction over the p95's implicit 5% budget), the
replay-parity test proves the live daemon and ``registry_from_ledger``
feed the identical ``tmx_slo_*`` series, and the exit codes are pinned
like the other sentinels (qc, bench_regression).
"""

import json
import math
import random

import pytest

from tmlibrary_tpu import slo, telemetry


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    """No TMX_SLO_* leakage between tests (or from the invoking shell)."""
    import os

    for k in list(os.environ):
        if k.startswith("TMX_SLO_"):
            monkeypatch.delenv(k, raising=False)
    yield


# ------------------------------------------------------------- objectives
def test_objectives_defaults_come_from_config():
    obj = slo.objectives("anyone")
    assert obj.latency_p95_s == 600.0
    assert obj.availability == 0.99
    assert obj.windows == (3600.0, 21600.0)


def test_objectives_env_overrides_and_per_tenant(monkeypatch):
    monkeypatch.setenv("TMX_SLO_LATENCY_P95_S", "10")
    monkeypatch.setenv("TMX_SLO_LATENCY_P95_S_PROD", "5")
    monkeypatch.setenv("TMX_SLO_AVAILABILITY", "0.9")
    monkeypatch.setenv("TMX_SLO_WINDOWS", "60, 120")
    assert slo.objectives("dev").latency_p95_s == 10.0
    assert slo.objectives("prod").latency_p95_s == 5.0
    assert slo.objectives("prod").availability == 0.9
    assert slo.objectives("dev").windows == (60.0, 120.0)
    # tenant names normalize to env-var alphabet: team-b -> TEAM_B
    monkeypatch.setenv("TMX_SLO_LATENCY_P95_S_TEAM_B", "7")
    assert slo.objectives("team-b").latency_p95_s == 7.0


def test_objectives_garbage_env_degrades_to_config(monkeypatch):
    monkeypatch.setenv("TMX_SLO_LATENCY_P95_S", "not-a-number")
    monkeypatch.setenv("TMX_SLO_WINDOWS", "bogus,,")
    obj = slo.objectives()
    assert obj.latency_p95_s == 600.0
    assert obj.windows == (3600.0,)  # unparseable spec -> safe fallback


# --------------------------------------------------------------- quantile
def test_quantile_nearest_rank():
    assert slo.quantile([], 0.5) is None
    assert slo.quantile([5.0], 0.95) == 5.0
    assert slo.quantile([4.0, 1.0, 3.0, 2.0], 0.5) == 2.0
    assert slo.quantile([4.0, 1.0, 3.0, 2.0], 0.95) == 4.0
    # rank math: ceil(0.95 * 9) = 9 -> the largest of nine
    assert slo.quantile(list(map(float, range(1, 10))), 0.95) == 9.0


# ----------------------------------------------------------------- report
def _fixture_events():
    """Ten tenant-a completions inside one 100 s window: 8 fast ok,
    1 slow ok (3 s > the 2 s objective), 1 failed.  Hand computation at
    latency_p95_s=2, availability=0.9, window=100:

    * availability burn = (1/10) / (1 - 0.9)  = 1.0
    * latency burn      = (1/10) / 0.05       = 2.0
    * tenant burn = max = 2.0  -> breach
    * p50 over [1.0 x8, 3.0] = 1.0 ; p95 = 3.0 ; availability = 0.9
    """
    events = []
    for i in range(8):
        events.append({"host": "h0", "ts": 10.0 + i,
                       "event": "job_done", "job": f"a-{i}",
                       "tenant": "a", "elapsed_s": 1.0})
    events.append({"host": "h0", "ts": 50.0, "event": "job_done",
                   "job": "a-slow", "tenant": "a", "elapsed_s": 3.0})
    events.append({"host": "h0", "ts": 60.0, "event": "job_failed",
                   "job": "a-bad", "tenant": "a", "error": "boom"})
    return events


def test_report_hand_computed_burn_fixture(monkeypatch):
    monkeypatch.setenv("TMX_SLO_LATENCY_P95_S", "2")
    monkeypatch.setenv("TMX_SLO_AVAILABILITY", "0.9")
    monkeypatch.setenv("TMX_SLO_WINDOWS", "100")
    view = slo.report(_fixture_events())
    assert view["now"] == 60.0  # defaults to the newest completion ts
    t = view["tenants"]["a"]
    assert t["jobs"] == {"ok": 9, "failed": 1, "expired": 0, "total": 10}
    assert t["latency_p50_s"] == 1.0
    assert t["latency_p95_s"] == 3.0
    assert t["availability"] == 0.9
    w = t["windows"]["100"]
    assert w == {"total": 10, "bad": 1, "slow": 1,
                 "availability_burn": 1.0, "latency_burn": 2.0,
                 "burn": 2.0}
    assert t["burn"] == 2.0 and t["breach"] is True
    assert slo.breaches(view) == [
        {"tenant": "a", "window": "100", "burn": 2.0}]
    assert slo.exit_code(view) == slo.EXIT_BURN
    assert "** BURN **" in slo.render(view)
    # the whole view is JSON-serializable (tmx slo --json, top --json)
    json.dumps(view)


def test_report_order_independent_and_host_deduped(monkeypatch):
    monkeypatch.setenv("TMX_SLO_WINDOWS", "100")
    events = _fixture_events()
    base = slo.report(events, now=60.0)
    shuffled = list(events)
    random.Random(7).shuffle(shuffled)
    # shuffled + duplicated (same host ledger read twice) must not move
    # a single number — the merge discipline fleet ledgers rely on
    assert slo.report(shuffled + events, now=60.0) == base


def test_report_zero_burn_and_no_data(monkeypatch):
    monkeypatch.setenv("TMX_SLO_WINDOWS", "100")
    events = [{"host": "h0", "ts": float(i), "event": "job_done",
               "job": f"j{i}", "tenant": "a", "elapsed_s": 0.5}
              for i in range(4)]
    view = slo.report(events)
    t = view["tenants"]["a"]
    assert t["burn"] == 0.0 and t["breach"] is False
    assert slo.exit_code(view) == slo.EXIT_OK
    assert slo.breaches(view) == []
    empty = slo.report([])
    assert slo.exit_code(empty) == slo.EXIT_NO_DATA
    assert "no job-completion events" in slo.render(empty)


def test_availability_burn_inf_at_perfect_objective(monkeypatch):
    """availability=1.0 leaves zero error budget: one failure is an
    immediately-infinite burn, rendered as the JSON-safe string 'inf'."""
    monkeypatch.setenv("TMX_SLO_AVAILABILITY", "1.0")
    monkeypatch.setenv("TMX_SLO_WINDOWS", "100")
    events = [
        {"host": "h0", "ts": 1.0, "event": "job_done", "job": "j1",
         "tenant": "a", "elapsed_s": 0.1},
        {"host": "h0", "ts": 2.0, "event": "job_failed", "job": "j2",
         "tenant": "a"},
    ]
    view = slo.report(events)
    w = view["tenants"]["a"]["windows"]["100"]
    assert w["availability_burn"] == "inf" and w["burn"] == "inf"
    assert view["tenants"]["a"]["breach"] is True
    assert slo.exit_code(view) == slo.EXIT_BURN
    assert slo._burn_value("inf") == math.inf
    json.dumps(view)


def test_window_scoping_old_completions_age_out(monkeypatch):
    """Only completions inside each window count toward its burn: a
    failure 1000 s ago burns the 100 s window not at all and the 2000 s
    window fully."""
    monkeypatch.setenv("TMX_SLO_AVAILABILITY", "0.5")
    monkeypatch.setenv("TMX_SLO_WINDOWS", "100,2000")
    events = [
        {"host": "h0", "ts": 1000.0, "event": "job_failed", "job": "old",
         "tenant": "a"},
        {"host": "h0", "ts": 1990.0, "event": "job_done", "job": "new",
         "tenant": "a", "elapsed_s": 0.1},
    ]
    view = slo.report(events, now=2000.0)
    t = view["tenants"]["a"]
    assert t["windows"]["100"] == {
        "total": 1, "bad": 0, "slow": 0, "availability_burn": 0.0,
        "latency_burn": 0.0, "burn": 0.0}
    # 2000 s window: bad 1 of 2 -> (0.5)/(1-0.5) = 1.0
    assert t["windows"]["2000"]["burn"] == 1.0
    assert t["burn"] == 1.0 and t["breach"] is True


# ---------------------------------------------------------- replay parity
def test_replay_parity_observe_job_vs_registry_from_ledger():
    """The live daemon's observe_job calls and registry_from_ledger over
    the same ledger must produce identical tmx_slo_* series — one
    definition, two feeders."""
    events = [
        {"host": "h0", "ts": 1.0, "event": "job_done", "job": "a-1",
         "tenant": "a", "elapsed_s": 2.5},
        {"host": "h0", "ts": 2.0, "event": "job_done", "job": "b-1",
         "tenant": "b", "elapsed_s": 0.5},
        {"host": "h0", "ts": 3.0, "event": "job_failed", "job": "a-2",
         "tenant": "a", "error": "boom"},
        {"host": "h0", "ts": 4.0, "event": "job_expired", "job": "b-2",
         "tenant": "b"},
    ]
    live = telemetry.MetricsRegistry(enabled=True)
    # exactly what serve.py does at each completion
    slo.observe_job(live, "a", "ok", 2.5, host="h0")
    slo.observe_job(live, "b", "ok", 0.5, host="h0")
    slo.observe_job(live, "a", "failed", None, host="h0")
    slo.observe_job(live, "b", "expired", None, host="h0")
    replay = telemetry.registry_from_ledger(events)
    for tenant, outcome in (("a", "ok"), ("b", "ok"),
                            ("a", "failed"), ("b", "expired")):
        assert (replay.counter("tmx_slo_jobs_total", tenant=tenant,
                               outcome=outcome, host="h0").value
                == live.counter("tmx_slo_jobs_total", tenant=tenant,
                                outcome=outcome, host="h0").value == 1)
    for tenant, total in (("a", 2.5), ("b", 0.5)):
        hr = replay.histogram("tmx_slo_job_latency_seconds",
                              tenant=tenant, host="h0")
        hv = live.histogram("tmx_slo_job_latency_seconds",
                            tenant=tenant, host="h0")
        assert hr.count == hv.count == 1
        assert hr.sum == pytest.approx(hv.sum) == pytest.approx(total)
