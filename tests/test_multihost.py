"""REAL multi-process distributed runtime test (SURVEY.md §6 "Distributed
communication backend"): two OS processes, each a simulated host with 2
CPU devices, bootstrap ``jax.distributed`` over a localhost coordinator
with gloo collectives and run one jitted jterator pipeline over the
global hybrid mesh.  This is the path a v5e pod launch takes — every
prior distributed test ran single-process on a forced 8-device backend;
this one crosses actual process boundaries."""
import ast
import re
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_pipeline_over_pod_mesh():
    # hang protection comes from communicate(timeout=240) below — both
    # workers are killed in finally if the coordinator wedges
    port = _free_port()
    procs = []
    for pid in range(2):
        env = {
            **os.environ,
            # the env-var bootstrap path of parallel.distributed.initialize
            "JAX_COORDINATOR_ADDRESS": f"localhost:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
        }
        env.pop("XLA_FLAGS", None)  # worker sets its own device count
        procs.append(subprocess.Popen(
            [sys.executable, WORKER],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        ))
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outputs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                # drain the pipes after kill so a wedged coordinator is
                # diagnosable from the failure output
                out, _ = p.communicate()
                print(f"--- killed worker output ---\n{out[-3000:]}")
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"WORKER_OK process={pid}" in out, out[-2000:]
    # both workers computed over the same global mesh: each host's shard
    # holds 4 real (non-zero) per-site counts for ITS slice
    # regex-bounded: stderr is merged into stdout and gloo's info
    # chatter can land on the SAME line as the worker's print — a bare
    # split would feed the chatter to literal_eval (flaked under load)
    counts = []
    for out in outputs:
        for line in out.splitlines():
            if "WORKER_OK" not in line:
                continue
            m = re.search(r"counts=(\[[0-9,\s]*\])", line)
            assert m, f"WORKER_OK line without parseable counts: {line!r}"
            counts.append(ast.literal_eval(m.group(1)))
    assert len(counts) == 2
    for shard in counts:
        assert len(shard) == 4 and all(c > 0 for c in shard), counts
    # the 2-D spatially-sharded CC stage crossed the process boundary
    # on both workers (seam joins + corner merge over gloo)
    for pid, out in enumerate(outputs):
        assert f"CC2D_OK process={pid}" in out, out[-2000:]
    # the shard_map production batch path ran over the pod mesh too
    for pid, out in enumerate(outputs):
        assert f"SHARDMAP_OK process={pid}" in out, out[-2000:]
