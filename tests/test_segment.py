import jax
import jax.numpy as jnp
import numpy as np
import scipy.ndimage as ndi

from tmlibrary_tpu.ops.segment_primary import (
    distance_transform_approx,
    segment_primary,
)
from tmlibrary_tpu.ops.segment_secondary import (
    expand_labels,
    propagate_labels,
    watershed_from_seeds,
)


def two_cells(shape=(64, 64)):
    """Two bright nuclei inside two larger dim cells, touching in the middle."""
    yy, xx = np.mgrid[0 : shape[0], 0 : shape[1]]
    nuc = np.zeros(shape, np.float32)
    cell = np.zeros(shape, np.float32)
    for cy, cx in [(32, 20), (32, 44)]:
        nuc += 4000 * np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * 4.0**2))
        cell += 1500 * np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * 10.0**2))
    return nuc + 100, cell + nuc * 0.2 + 100


def test_segment_primary_counts_blobs():
    nuc, _ = two_cells()
    labels, count = segment_primary(jnp.asarray(nuc), threshold_method="manual",
                                    threshold_value=1000.0, smooth_sigma=1.0)
    assert int(count) == 2
    mask = ndi.gaussian_filter(nuc, 1.0, mode="reflect") > 1000
    expected, n = ndi.label(mask, ndi.generate_binary_structure(2, 2))
    np.testing.assert_array_equal(np.asarray(labels), expected)


def test_segment_primary_size_filter():
    img = np.full((64, 64), 100.0, np.float32)
    img[4:6, 4:6] = 5000  # area 4 (+ smoothing halo)
    img[20:40, 20:40] = 5000  # area 400
    labels, count = segment_primary(
        jnp.asarray(img), threshold_method="manual", threshold_value=2000.0,
        smooth_sigma=0.0, min_area=50,
    )
    assert int(count) == 1
    assert int((np.asarray(labels) > 0).sum()) == 400


def test_propagate_fills_mask():
    seeds = jnp.zeros((32, 32), jnp.int32).at[8, 8].set(1).at[24, 24].set(2)
    mask = jnp.ones((32, 32), bool)
    out = np.asarray(propagate_labels(seeds, mask))
    assert set(np.unique(out)) == {1, 2}
    assert out[8, 8] == 1 and out[24, 24] == 2


def test_expand_labels_distance():
    seeds = jnp.zeros((16, 16), jnp.int32).at[8, 8].set(3)
    out = np.asarray(expand_labels(seeds, iterations=2))
    assert out[8, 8] == 3 and out[6, 6] == 3 and out[8, 11] == 0


def test_watershed_splits_touching_cells():
    nuc, cell = two_cells()
    seeds, n = segment_primary(
        jnp.asarray(nuc), threshold_method="manual", threshold_value=1000.0
    )
    assert int(n) == 2
    mask = cell > 300
    labels = np.asarray(
        watershed_from_seeds(jnp.asarray(cell), seeds, jnp.asarray(mask), n_levels=32)
    )
    # both seeds grew, cover most of the mask, and split near the midline
    assert (labels == 1).sum() > 100 and (labels == 2).sum() > 100
    covered = (labels > 0).sum() / mask.sum()
    assert covered > 0.95
    # left cell is label of left seed, right cell label of right seed
    assert labels[32, 16] == labels[32, 20] == np.asarray(seeds)[32, 20]
    assert labels[32, 48] == labels[32, 44] == np.asarray(seeds)[32, 44]
    # border between the two regions sits near the intensity valley (x≈32)
    border_x = [
        x for x in range(64)
        if labels[32, x] > 0 and x + 1 < 64 and labels[32, x + 1] > 0
        and labels[32, x] != labels[32, x + 1]
    ]
    assert border_x and abs(border_x[0] - 32) <= 3


def test_watershed_respects_mask():
    seeds = jnp.zeros((32, 32), jnp.int32).at[16, 8].set(1)
    mask = np.zeros((32, 32), bool)
    mask[:, :16] = True  # wall at x=16
    intensity = jnp.ones((32, 32), jnp.float32)
    labels = np.asarray(watershed_from_seeds(intensity, seeds, jnp.asarray(mask)))
    assert labels[:, 16:].sum() == 0
    assert (labels[:, :16] == 1).all()


def test_distance_transform_monotone():
    mask = np.zeros((32, 32), bool)
    mask[8:24, 8:24] = True
    dist = np.asarray(distance_transform_approx(jnp.asarray(mask), max_distance=16))
    assert dist[16, 16] == dist.max()
    assert dist[8, 8] == 1.0  # corner pixel: eroded away after first ring
    assert (dist[~mask] == 0).all()


def test_segment_under_jit_vmap():
    nuc, cell = two_cells()
    batch_nuc = jnp.stack([jnp.asarray(nuc)] * 2)
    batch_cell = jnp.stack([jnp.asarray(cell)] * 2)

    @jax.jit
    @jax.vmap
    def run(n, c):
        seeds, cnt = segment_primary(n, threshold_method="manual", threshold_value=1000.0)
        cells = watershed_from_seeds(c, seeds, c > 300, n_levels=16)
        return cnt, cells

    cnt, cells = run(batch_nuc, batch_cell)
    assert list(np.asarray(cnt)) == [2, 2]
    assert np.asarray(cells).shape == (2, 64, 64)


def test_declump_labels_in_scan_order():
    """Declumped labels must follow scipy scan order (first pixel in
    row-major order -> label 1), not seed-peak discovery order
    (round-1 VERDICT weak item #7)."""
    from tmlibrary_tpu.ops.label import relabel_by_scan_order

    # two touching disks, the later-scanned one has the HIGHER peak so a
    # naive seed order would invert the ids
    yy, xx = np.mgrid[0:64, 0:64]
    img = np.zeros((64, 64), np.float32)
    img[((yy - 40) ** 2 + (xx - 22) ** 2) <= 100] = 2000.0
    img[((yy - 20) ** 2 + (xx - 34) ** 2) <= 100] = 1500.0
    labels, count = segment_primary(
        jnp.asarray(img), threshold_method="manual", threshold_value=500.0,
        smooth_sigma=0.0, declump=True, declump_min_distance=6, max_objects=8,
    )
    labels = np.asarray(labels)
    assert int(count) == 2
    # label 1 owns the first foreground pixel in scan order
    first_pix = np.argwhere(labels > 0)[0]
    assert labels[tuple(first_pix)] == 1
    # ids are ordered by each region's min linear index
    firsts = [np.flatnonzero((labels == l).ravel())[0] for l in (1, 2)]
    assert firsts == sorted(firsts)


def test_relabel_by_scan_order_matches_scipy_convention(rng):
    from tmlibrary_tpu.ops.label import relabel_by_scan_order

    # random permuted labeling of scipy components must map back exactly
    mask = ndi.binary_dilation(rng.random((48, 48)) > 0.92, iterations=2)
    want, n = ndi.label(mask, ndi.generate_binary_structure(2, 2))
    perm = np.concatenate([[0], rng.permutation(n) + 1])
    scrambled = perm[want]
    got = np.asarray(relabel_by_scan_order(jnp.asarray(scrambled), 64))
    np.testing.assert_array_equal(got, want)
