"""Randomized end-to-end parity fuzz: the fused segment chain must match
the scipy reference for COUNTS and LABELS across random parameter draws,
not just the golden fixtures' parameters (BASELINE bit-identical gate,
property-test tier — SURVEY §5's "exceed the reference here" decision).

Each case draws sigma, threshold correction, min_area, watershed levels,
cell count/size and image size, runs the same chain both ways, and
asserts bit-identical label images.  Seeded parametrization: failures
reproduce exactly.
"""

import numpy as np
import pytest
import scipy.ndimage as ndi

from tmlibrary_tpu.benchmarks import _otsu_numpy
from tmlibrary_tpu.ops.label import connected_components
from tmlibrary_tpu.ops.segment_primary import segment_primary
from tmlibrary_tpu.ops.segment_secondary import watershed_from_seeds
from tmlibrary_tpu.ops.smooth import gaussian_smooth


def _blob_image(rng, size, n_cells, radius):
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    img = rng.normal(300.0, 25.0, (size, size)).astype(np.float32)
    m = max(4, int(radius * 2))
    for _ in range(n_cells):
        y, x = rng.integers(m, size - m, 2)
        r = radius * rng.uniform(0.7, 1.3)
        img += 4000.0 * np.exp(
            -((yy - y) ** 2 + (xx - x) ** 2) / (2 * r**2)
        )
    return np.clip(img, 0, 65535)


def _scipy_primary(sm, min_area):
    mask = sm > _otsu_numpy(sm)
    mask = ndi.binary_fill_holes(mask)
    lab, _ = ndi.label(mask, structure=np.ones((3, 3)))
    sizes = np.bincount(lab.ravel())
    keep = np.flatnonzero(sizes >= min_area)[1:]
    remap = np.zeros(sizes.size, np.int32)
    remap[keep] = np.arange(1, keep.size + 1)
    return remap[lab]


@pytest.mark.parametrize("seed", range(8))
def test_primary_chain_parity_random_params(seed):
    rng = np.random.default_rng(1000 + seed)
    size = int(rng.choice([96, 128, 192]))
    sigma = float(rng.uniform(0.8, 2.5))
    min_area = int(rng.integers(5, 60))
    n_cells = int(rng.integers(2, 12))
    radius = float(rng.uniform(2.5, 6.0))

    img = _blob_image(rng, size, n_cells, radius)
    sm = np.asarray(gaussian_smooth(img, sigma))
    got = np.asarray(
        segment_primary(
            sm, threshold_method="otsu", smooth_sigma=0.0,
            min_area=min_area,
        )[0]
    )
    want = _scipy_primary(sm, min_area)
    np.testing.assert_array_equal(
        got, want,
        err_msg=f"seed={seed} size={size} sigma={sigma:.3f} "
                f"min_area={min_area} n_cells={n_cells} r={radius:.2f}",
    )


@pytest.mark.parametrize("seed", range(4))
def test_secondary_chain_parity_random_params(seed):
    """Watershed growth from random primaries: the xla path IS the
    golden here (native/pallas twins are asserted bit-identical to it
    elsewhere) — this fuzzes that the full chain stays deterministic and
    well-formed across parameter draws: labels cover every seed, stay
    inside the mask, and preserve seed identities."""
    rng = np.random.default_rng(2000 + seed)
    size = int(rng.choice([96, 128]))
    n_levels = int(rng.choice([8, 16, 32]))
    corr = float(rng.uniform(0.6, 1.0))

    dapi = _blob_image(rng, size, int(rng.integers(3, 9)), 4.0)
    actin = _blob_image(rng, size, int(rng.integers(3, 9)), 9.0)
    sm = np.asarray(gaussian_smooth(dapi, 1.5))
    seeds = np.asarray(
        segment_primary(sm, threshold_method="otsu", smooth_sigma=0.0,
                        min_area=20)[0]
    )
    if seeds.max() == 0:
        pytest.skip("draw produced no seeds")
    thr = _otsu_numpy(np.asarray(actin, np.float32)) * corr
    mask = actin > thr

    cells = np.asarray(watershed_from_seeds(
        actin, seeds, mask, n_levels=n_levels, method="xla"
    ))
    # seed pixels keep their labels
    np.testing.assert_array_equal(cells[seeds > 0], seeds[seeds > 0])
    # growth stays inside mask | seeds
    assert not np.any((cells > 0) & ~(mask | (seeds > 0)))
    # deterministic across a re-run
    again = np.asarray(watershed_from_seeds(
        actin, seeds, mask, n_levels=n_levels, method="xla"
    ))
    np.testing.assert_array_equal(cells, again)


# ------------------------------------------------- measurement fuzz
def _random_labels(rng, size):
    img = _blob_image(rng, size, int(rng.integers(3, 10)),
                      float(rng.uniform(3.0, 6.0)))
    sm = np.asarray(gaussian_smooth(img, 1.5))
    labels = np.asarray(
        segment_primary(sm, threshold_method="otsu", smooth_sigma=0.0,
                        min_area=10)[0]
    )
    return labels, np.asarray(img, np.float32)


@pytest.mark.parametrize("seed", range(6))
def test_measurement_parity_random_scenes(seed):
    """Intensity + morphology basics vs scipy.ndimage on random
    segmentations — the golden-fixture assertions, at fuzz breadth."""
    from tmlibrary_tpu.ops.measure import (
        intensity_features,
        morphology_features,
    )

    rng = np.random.default_rng(3000 + seed)
    size = int(rng.choice([96, 128, 160]))
    labels, img = _random_labels(rng, size)
    n = int(labels.max())
    if n == 0:
        pytest.skip("draw produced no objects")
    cap = max(8, n + 2)

    ints = intensity_features(labels, img, cap)
    morph = morphology_features(labels, cap)
    idx = np.arange(1, n + 1)

    np.testing.assert_allclose(
        np.asarray(ints["Intensity_mean"])[:n],
        ndi.mean(img, labels, idx), rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(ints["Intensity_sum"])[:n],
        ndi.sum(img, labels, idx), rtol=2e-5)
    np.testing.assert_array_equal(
        np.asarray(ints["Intensity_max"])[:n],
        ndi.maximum(img, labels, idx))
    np.testing.assert_array_equal(
        np.asarray(ints["Intensity_min"])[:n],
        ndi.minimum(img, labels, idx))
    np.testing.assert_allclose(
        np.asarray(ints["Intensity_std"])[:n],
        ndi.standard_deviation(img, labels, idx), rtol=1e-3, atol=1e-4)

    areas = np.array([(labels == l).sum() for l in idx], np.float64)
    np.testing.assert_array_equal(
        np.asarray(morph["Morphology_area"])[:n], areas)
    cy = ndi.center_of_mass(np.ones_like(labels), labels, idx)
    np.testing.assert_allclose(
        np.asarray(morph["Morphology_centroid_y"])[:n],
        [c[0] for c in cy], rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(morph["Morphology_centroid_x"])[:n],
        [c[1] for c in cy], rtol=1e-5, atol=1e-4)
    # bbox vs find_objects
    sl = ndi.find_objects(labels)
    bh = [s[0].stop - s[0].start for s in sl if s is not None]
    bw = [s[1].stop - s[1].start for s in sl if s is not None]
    np.testing.assert_array_equal(
        np.asarray(morph["Morphology_bbox_height"])[:n], bh)
    np.testing.assert_array_equal(
        np.asarray(morph["Morphology_bbox_width"])[:n], bw)


# ------------------------------------------------- 3-D volume fuzz
@pytest.mark.parametrize("seed", range(4))
def test_volume_cc_parity_random_draws(seed):
    """3-D connected components vs scipy.ndimage at random blob draws,
    all three connectivities, both the auto (native-on-cpu) and xla
    paths — bit-identical label volumes."""
    from tmlibrary_tpu.ops.volume import connected_components_3d

    rng = np.random.default_rng(4000 + seed)
    nz = int(rng.choice([6, 10, 14]))
    size = int(rng.choice([48, 64]))
    zz, yy, xx = np.mgrid[0:nz, 0:size, 0:size].astype(np.float32)
    vol = rng.normal(0.0, 0.05, (nz, size, size)).astype(np.float32)
    for _ in range(int(rng.integers(3, 8))):
        z, y, x = rng.integers(2, nz - 2), *rng.integers(8, size - 8, 2)
        r = float(rng.uniform(2.0, 4.0))
        vol += np.exp(-(((zz - z) * 2.0) ** 2 + (yy - y) ** 2
                        + (xx - x) ** 2) / (2 * r**2))
    mask = vol > 0.35

    for conn in (6, 18, 26):
        struct = ndi.generate_binary_structure(3, {6: 1, 18: 2, 26: 3}[conn])
        want, n_want = ndi.label(mask, structure=struct)
        for method in ("auto", "xla"):
            got, n = connected_components_3d(mask, conn, method=method)
            assert int(n) == n_want, (seed, conn, method)
            np.testing.assert_array_equal(
                np.asarray(got), want,
                err_msg=f"seed={seed} conn={conn} method={method}")


# --------------------------------------------- third-party cross-checks
@pytest.mark.parametrize("seed", range(4))
def test_cc_count_matches_opencv_too(seed):
    """Component counts vs BOTH scipy and OpenCV (independent lineages)
    on random draws — connectivity 8 and 4."""
    import cv2

    rng = np.random.default_rng(6000 + seed)
    mask = (_blob_image(rng, 96, int(rng.integers(3, 10)), 4.0) > 650)

    for conn in (8, 4):
        _, n_ours = connected_components(mask, conn, method="xla")
        struct = ndi.generate_binary_structure(2, 2 if conn == 8 else 1)
        n_scipy = ndi.label(mask, struct)[1]
        n_cv = cv2.connectedComponents(
            mask.astype(np.uint8), connectivity=conn)[0] - 1
        assert int(n_ours) == n_scipy == n_cv, (seed, conn)


@pytest.mark.parametrize("seed", range(3))
def test_distance_matches_opencv_chessboard(seed):
    """Chessboard distance vs cv2.distanceTransform(DIST_C) on interior
    pixels (borders differ by design: erosion counting treats
    out-of-image as foreground)."""
    import cv2

    from tmlibrary_tpu.ops.segment_primary import distance_transform_approx

    rng = np.random.default_rng(7000 + seed)
    mask = _blob_image(rng, 96, 6, 6.0) > 600
    got = np.asarray(distance_transform_approx(mask, method="xla"))
    want = cv2.distanceTransform(
        mask.astype(np.uint8), cv2.DIST_C, cv2.DIST_MASK_PRECISE)
    interior = np.zeros_like(mask)
    interior[10:-10, 10:-10] = True
    np.testing.assert_array_equal(got[interior], want[interior])


@pytest.mark.parametrize("seed", range(6))
def test_otsu_matches_opencv_within_a_bin(seed):
    """Otsu threshold vs cv2.THRESH_OTSU on uint8 draws: ours bins over
    the data's [min, max] and returns a fractional edge while cv2 uses
    fixed integer 0-255 bins, so agreement within ~1.5 gray levels is
    the exact-match expectation — a real divergence would be tens of
    levels."""
    import cv2

    from tmlibrary_tpu.ops.threshold import otsu_value

    rng = np.random.default_rng(8000 + seed)
    yy, xx = np.mgrid[0:96, 0:96].astype(np.float32)
    img = rng.normal(80, 10, (96, 96)).astype(np.float32)
    for _ in range(6):
        y, x = rng.integers(10, 86, 2)
        img += 120 * np.exp(-((yy - y) ** 2 + (xx - x) ** 2) / 18)
    u8 = np.clip(img, 0, 255).astype(np.uint8)
    ours = float(np.asarray(otsu_value(u8.astype(np.float32))))
    cvt, _ = cv2.threshold(u8, 0, 255, cv2.THRESH_BINARY + cv2.THRESH_OTSU)
    assert abs(ours - float(cvt)) <= 1.5, (seed, ours, cvt)


@pytest.mark.parametrize("size", [3, 5])
def test_median_matches_opencv_interior(size):
    """Median filter vs cv2.medianBlur on uint8 (exact on interior
    pixels; border conventions differ — scipy reflects, cv2 replicates)."""
    import cv2

    from tmlibrary_tpu.ops.smooth import median_smooth

    rng = np.random.default_rng(9000 + size)
    u8 = rng.integers(0, 256, (64, 64), np.uint8)
    ours = np.asarray(median_smooth(u8.astype(np.float32), size))
    cvm = cv2.medianBlur(u8, size).astype(np.float32)
    k = size // 2
    np.testing.assert_array_equal(ours[k:-k, k:-k], cvm[k:-k, k:-k])
