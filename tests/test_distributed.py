"""Multi-host runtime helpers on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tmlibrary_tpu.parallel.distributed import (
    batch_spec,
    global_to_host_local,
    host_local_to_global,
    initialize,
    local_site_slice,
    pod_mesh,
    sync_hosts,
)


def test_initialize_single_host_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert initialize() is False
    # explicit single-process is also a no-op
    assert initialize("127.0.0.1:9999", num_processes=1, process_id=0) is False


def test_initialize_partial_config_fails_fast(monkeypatch):
    """A pod launch script that sets only half the coordinator config must
    error, not silently run every host as an independent single-host job."""
    import pytest

    from tmlibrary_tpu.errors import ShardingError

    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    with pytest.raises(ShardingError):
        initialize("10.0.0.1:1234")
    with pytest.raises(ShardingError):
        initialize(num_processes=4, process_id=0)


def test_pod_mesh_default(devices):
    mesh = pod_mesh()
    assert mesh.axis_names == ("wells", "sites")
    assert mesh.devices.size == 8
    # single host: wells defaults to process_count=1
    assert mesh.shape["wells"] == 1 and mesh.shape["sites"] == 8


def test_pod_mesh_explicit_wells(devices):
    mesh = pod_mesh(wells=4)
    assert mesh.shape["wells"] == 4 and mesh.shape["sites"] == 2
    with pytest.raises(ValueError):
        pod_mesh(wells=3)


def test_batch_shards_over_pod_mesh(devices):
    mesh = pod_mesh(wells=2)
    batch = np.arange(16 * 4 * 4, dtype=np.float32).reshape(16, 4, 4)
    spec = batch_spec(mesh)
    sharded = jax.device_put(
        batch, jax.sharding.NamedSharding(mesh, spec)
    )
    assert len(sharded.addressable_shards) == 8
    assert sharded.addressable_shards[0].data.shape == (2, 4, 4)
    # computation over the sharded axis matches unsharded
    out = jax.jit(lambda x: jnp.sum(x, axis=(1, 2)))(sharded)
    np.testing.assert_allclose(np.asarray(out), batch.sum(axis=(1, 2)))


def test_local_site_slice_partitions_everything():
    n_sites = 37
    covered = []
    for pid in range(4):
        s = local_site_slice(n_sites, process_id=pid, n_processes=4)
        covered.extend(range(*s.indices(n_sites)))
    assert covered == list(range(n_sites))
    # single-process: the whole range
    s = local_site_slice(10, process_id=0, n_processes=1)
    assert (s.start, s.stop) == (0, 10)


def test_host_local_global_round_trip(devices):
    mesh = pod_mesh()
    local = np.random.default_rng(0).normal(size=(8, 4, 4)).astype(np.float32)
    g = host_local_to_global(local, mesh)
    assert g.shape == (8, 4, 4)
    back = global_to_host_local(g, mesh)
    np.testing.assert_array_equal(back, local)


def test_sync_hosts_single_host_noop():
    sync_hosts("test")  # must not raise or hang on one host


def test_collective_budget_counts_root_collectives():
    """A collective emitted as a computation ROOT still counts (round-4
    advisor: the old regex required the line to START with the name, so
    `ROOT %x = ... all-gather(...)` was silently uncounted and the
    zero-collectives guarantee could false-pass)."""
    from scripts.comm_budget import collective_budget

    hlo = "\n".join([
        "  %x = f32[8]{0} add(%a, %b)",
        "  ROOT %ag = f32[2,64]{1,0} all-gather(%x), dimensions={0}",
        "  %ar.1 = f32[4]{0} all-reduce-start(%y), to_apply=%sum",
    ])
    budget = collective_budget(hlo)
    assert budget["all-gather"] == {"count": 1, "bytes": 2 * 64 * 4}
    assert budget["all-reduce"]["count"] == 1


def test_sharded_batch_fn_is_communication_free(devices):
    """The production multi-chip jterator path
    (``build_sharded_batch_fn``) must compile to ZERO collectives —
    GSPMD-through-vmap instead all-gathers the batch-sharded while-loop
    state every trip — and must equal the single-device result exactly."""
    from jax.sharding import NamedSharding, PartitionSpec

    from scripts.comm_budget import collective_budget
    from tmlibrary_tpu.benchmarks import (
        cell_painting_description,
        synthetic_cell_painting_batch,
    )
    from tmlibrary_tpu.jterator.pipeline import ImageAnalysisPipeline
    from tmlibrary_tpu.parallel.mesh import site_mesh

    mesh = site_mesh(8)
    pipe = ImageAnalysisPipeline(cell_painting_description(), max_objects=16)
    data = synthetic_cell_painting_batch(16, size=64, n_cells=4)
    shard = NamedSharding(mesh, PartitionSpec("sites"))
    raw = {k: jax.device_put(jnp.asarray(v), shard) for k, v in data.items()}
    shifts = jax.device_put(jnp.zeros((16, 2), jnp.int32), shard)

    sfn = pipe.build_sharded_batch_fn(mesh)
    compiled = sfn.lower(raw, {}, shifts).compile()
    assert collective_budget(compiled.as_text()) == {}

    res = compiled(raw, {}, shifts)
    single = pipe.build_batch_fn()(
        {k: jax.device_put(v, devices[0]) for k, v in raw.items()},
        {},
        jax.device_put(shifts, devices[0]),
    )
    for key in ("nuclei", "cells"):
        np.testing.assert_array_equal(
            np.asarray(res.counts[key]), np.asarray(single.counts[key])
        )
    feat = "Intensity_mean_DAPI"
    np.testing.assert_array_equal(
        np.asarray(res.measurements["nuclei"][feat]),
        np.asarray(single.measurements["nuclei"][feat]),
    )
