"""Bit-identity of the native CPU-fallback segmentation path (round-2
VERDICT next-step #2): on the cpu backend, ``method="auto"`` routes the
iterative ops through native/tmnative.cpp via ``jax.pure_callback``; every
kernel must reproduce the XLA twin EXACTLY (labels, not just counts),
because the pallas/xla/native trio all feed the same bit-identical gate."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tmlibrary_tpu import native
from tmlibrary_tpu.ops.label import connected_components, fill_holes
from tmlibrary_tpu.ops.segment_primary import distance_transform_approx
from tmlibrary_tpu.ops.segment_secondary import watershed_from_seeds

pytestmark = pytest.mark.skipif(
    not native.cpu_native_enabled(),
    reason="native CPU segmentation kernels unavailable",
)


def _blob_mask(rng, size=96, n_blobs=12):
    mask = np.zeros((size, size), bool)
    yy, xx = np.mgrid[:size, :size]
    for _ in range(n_blobs):
        cy, cx = rng.integers(4, size - 4, 2)
        r = rng.integers(3, 11)
        mask |= (yy - cy) ** 2 + (xx - cx) ** 2 <= r**2
    return mask


@pytest.fixture()
def rng():
    return np.random.default_rng(7)


@pytest.mark.parametrize("connectivity", [4, 8])
def test_cc_native_matches_xla(rng, connectivity):
    for trial in range(5):
        mask = _blob_mask(rng)
        ln, cn = connected_components(mask, connectivity, method="native")
        lx, cx = connected_components(mask, connectivity, method="xla")
        np.testing.assert_array_equal(np.asarray(ln), np.asarray(lx))
        assert int(cn) == int(cx)


def test_cc_native_under_jit_vmap(rng):
    batch = np.stack([_blob_mask(rng) for _ in range(4)])

    def run(b, method):
        return jax.jit(
            jax.vmap(lambda m: connected_components(m, 8, method=method))
        )(b)

    ln, cn = run(batch, "native")
    lx, cx = run(batch, "xla")
    np.testing.assert_array_equal(np.asarray(ln), np.asarray(lx))
    np.testing.assert_array_equal(np.asarray(cn), np.asarray(cx))


@pytest.mark.parametrize("connectivity", [4, 8])
def test_fill_holes_native_matches_xla(rng, connectivity):
    for trial in range(5):
        mask = _blob_mask(rng)
        # punch holes so there is something to fill
        mask &= ~_blob_mask(rng, n_blobs=20) | _blob_mask(rng, n_blobs=3)
        fn = fill_holes(mask, connectivity, method="native")
        fx = fill_holes(mask, connectivity, method="xla")
        np.testing.assert_array_equal(np.asarray(fn), np.asarray(fx))


@pytest.mark.parametrize("max_distance", [4, 64])
def test_distance_native_matches_xla(rng, max_distance):
    for trial in range(5):
        mask = _blob_mask(rng)
        dn = distance_transform_approx(mask, max_distance, method="native")
        dx = distance_transform_approx(mask, max_distance, method="xla")
        np.testing.assert_array_equal(np.asarray(dn), np.asarray(dx))


@pytest.mark.parametrize("max_distance", [8, 64])
def test_distance_native_all_foreground(max_distance):
    """No background -> nothing erodes; with max_distance > h+w the naive
    chamfer cap would leak the INF sentinel into the clamp (review catch)."""
    mask = np.ones((17, 23), bool)
    dn = distance_transform_approx(mask, max_distance, method="native")
    dx = distance_transform_approx(mask, max_distance, method="xla")
    np.testing.assert_array_equal(np.asarray(dn), np.asarray(dx))


@pytest.mark.parametrize("n_levels", [8, 32])
def test_watershed_native_matches_xla(rng, n_levels):
    for trial in range(5):
        size = 96
        mask = _blob_mask(rng, size)
        intensity = rng.normal(size=(size, size)).astype(np.float32)
        intensity += 3.0 * mask
        seeds = np.zeros((size, size), np.int32)
        ys, xs = np.nonzero(mask)
        for i, k in enumerate(
            rng.choice(len(ys), size=min(9, len(ys)), replace=False)
        ):
            seeds[ys[k], xs[k]] = i + 1
        wn = watershed_from_seeds(
            intensity, seeds, mask, n_levels=n_levels, method="native"
        )
        wx = watershed_from_seeds(
            intensity, seeds, mask, n_levels=n_levels, method="xla"
        )
        np.testing.assert_array_equal(np.asarray(wn), np.asarray(wx))


def test_watershed_native_under_jit(rng):
    size = 64
    mask = _blob_mask(rng, size)
    intensity = (rng.random((size, size)) * mask).astype(np.float32)
    seeds = np.zeros((size, size), np.int32)
    seeds[10, 10] = 1
    seeds[40, 40] = 2

    def run(im, sd, mk, method):
        return jax.jit(
            lambda a, b, c: watershed_from_seeds(a, b, c, n_levels=16, method=method)
        )(im, sd, mk)

    np.testing.assert_array_equal(
        np.asarray(run(intensity, seeds, mask, "native")),
        np.asarray(run(intensity, seeds, mask, "xla")),
    )


def test_auto_resolves_native_on_cpu():
    assert jax.default_backend() == "cpu"
    assert native.cpu_native_enabled()


def test_env_override_disables_native(monkeypatch):
    monkeypatch.setenv("TMX_NATIVE", "0")
    assert not native.cpu_native_enabled()


#: a stale prebuilt library can hold the 2-D kernels without the 3-D ones
needs_3d = pytest.mark.skipif(
    not native.has_3d_kernels(),
    reason="native 3-D segmentation kernels unavailable",
)


def _blob_volume(rng, z=12, size=48, n_blobs=8):
    vol = np.zeros((z, size, size), bool)
    zz, yy, xx = np.mgrid[:z, :size, :size]
    for _ in range(n_blobs):
        cz = rng.integers(2, z - 2)
        cy, cx = rng.integers(4, size - 4, 2)
        r = rng.integers(2, 6)
        vol |= (zz - cz) ** 2 + (yy - cy) ** 2 + (xx - cx) ** 2 <= r**2
    return vol


@needs_3d
@pytest.mark.parametrize("connectivity", [6, 18, 26])
def test_cc3d_native_matches_xla(rng, connectivity):
    from tmlibrary_tpu.ops.volume import connected_components_3d

    for trial in range(3):
        vol = _blob_volume(rng)
        ln, cn = connected_components_3d(vol, connectivity, method="native")
        lx, cx = connected_components_3d(vol, connectivity, method="xla")
        np.testing.assert_array_equal(np.asarray(ln), np.asarray(lx))
        assert int(cn) == int(cx)


@needs_3d
def test_cc3d_native_matches_scipy(rng):
    import scipy.ndimage as ndi

    from tmlibrary_tpu.ops.volume import connected_components_3d

    vol = _blob_volume(rng)
    ln, cn = connected_components_3d(vol, 26, method="native")
    golden, n = ndi.label(vol, structure=np.ones((3, 3, 3)))
    assert int(cn) == n
    np.testing.assert_array_equal(np.asarray(ln), golden)


@needs_3d
@pytest.mark.parametrize("n_levels", [4, 16])
def test_watershed3d_native_matches_xla(rng, n_levels):
    from tmlibrary_tpu.ops.volume import watershed_from_seeds_3d

    for trial in range(3):
        z, size = 10, 40
        vol = _blob_volume(rng, z, size)
        intensity = rng.normal(size=(z, size, size)).astype(np.float32)
        intensity += 3.0 * vol
        seeds = np.zeros((z, size, size), np.int32)
        zs, ys, xs = np.nonzero(vol)
        for i, k in enumerate(
            rng.choice(len(zs), size=min(6, len(zs)), replace=False)
        ):
            seeds[zs[k], ys[k], xs[k]] = i + 1
        wn = watershed_from_seeds_3d(
            intensity, seeds, vol, n_levels=n_levels, method="native"
        )
        wx = watershed_from_seeds_3d(
            intensity, seeds, vol, n_levels=n_levels, method="xla"
        )
        np.testing.assert_array_equal(np.asarray(wn), np.asarray(wx))
