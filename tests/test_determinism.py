"""Determinism guarantees (SURVEY.md §6: the reference relied on DB
transactions + idempotent re-runs; here JAX purity must make every
pipeline bit-reproducible — same inputs, same program, same bits)."""

import jax.numpy as jnp
import numpy as np

from tmlibrary_tpu.benchmarks import (
    cell_painting_description,
    synthetic_cell_painting_batch,
)
from tmlibrary_tpu.jterator.pipeline import ImageAnalysisPipeline


def _run_once(max_objects=32):
    data = synthetic_cell_painting_batch(4, size=96, n_cells=6)
    pipe = ImageAnalysisPipeline(cell_painting_description(), max_objects=max_objects)
    fn = pipe.build_batch_fn(jit=False)
    raw = {k: jnp.asarray(v) for k, v in data.items()}
    return fn(raw, {}, jnp.zeros((4, 2), jnp.int32))


def test_pipeline_bit_reproducible():
    a = _run_once()
    b = _run_once()
    for name in a.objects:
        np.testing.assert_array_equal(np.asarray(a.objects[name]),
                                      np.asarray(b.objects[name]))
    for obj, feats in a.measurements.items():
        counts = np.asarray(a.counts[obj])
        for fname, arr in feats.items():
            x, y = np.asarray(arr), np.asarray(b.measurements[obj][fname])
            # only rows below each site's object count are defined
            for s in range(x.shape[0]):
                n = int(counts[s])
                np.testing.assert_array_equal(x[s, :n], y[s, :n], err_msg=fname)


def test_rerun_step_idempotent(tmp_path, rng):
    """Re-running a jterator batch overwrites (not appends) its outputs —
    the idempotency the reference got from delete_previous_job_output."""
    from tmlibrary_tpu.models.experiment import grid_experiment
    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.workflow.registry import get_step
    import yaml

    exp = grid_experiment(name="d", well_rows=1, well_cols=1,
                          sites_per_well=(2, 2), channel_names=("DAPI",),
                          site_shape=(64, 64))
    store = ExperimentStore.create(tmp_path / "exp", exp)
    yy, xx = np.mgrid[0:64, 0:64]
    imgs = rng.normal(300, 20, (4, 64, 64))
    for s in range(4):
        for _ in range(5):
            y, x = rng.integers(8, 56, 2)
            imgs[s] += 4000 * np.exp(-((yy - y) ** 2 + (xx - x) ** 2) / (2 * 9.0))
    store.write_sites(np.clip(imgs, 0, 65535).astype(np.uint16),
                      list(range(4)), channel=0)

    pipe = {
        "description": "d",
        "input": {"channels": [{"name": "DAPI", "correct": False}]},
        "pipeline": [
            {"handles": {"module": "segment_primary", "input": [
                {"name": "intensity_image", "type": "IntensityImage",
                 "key": "DAPI"},
                {"name": "min_area", "type": "Numeric", "value": 5}],
                "output": [{"name": "objects", "type": "SegmentedObjects",
                            "key": "nuclei", "objects": "nuclei"}]}},
            {"handles": {"module": "measure_intensity", "input": [
                {"name": "objects_image", "type": "LabelImage", "key": "nuclei"},
                {"name": "intensity_image", "type": "IntensityImage",
                 "key": "DAPI"}],
                "output": [{"name": "measurements", "type": "Measurement",
                            "objects": "nuclei", "channel": "DAPI"}]}},
        ],
        "output": {"objects": [{"name": "nuclei"}]},
    }
    (store.root / "d.pipe.yaml").write_text(yaml.safe_dump(pipe))

    args = {"pipe": "d.pipe.yaml", "batch_size": 4, "max_objects": 32}
    step = get_step("jterator")(store)
    step.init(args)
    step.run(0)
    labels1 = store.read_labels(None, "nuclei").copy()
    feats1 = store.read_features("nuclei")

    # second run of the same batch: identical store state, no duplication
    step2 = get_step("jterator")(store)
    step2.init(args)
    step2.run(0)
    labels2 = store.read_labels(None, "nuclei")
    feats2 = store.read_features("nuclei")
    np.testing.assert_array_equal(labels1, labels2)
    assert len(feats1) == len(feats2)
