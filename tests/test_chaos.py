"""Chaos suite: the full canonical pipeline under deterministic injected
faults (``tmlibrary_tpu.faults``).

The property these tests pin down is *convergence*: a run that loses
batches to injected device/IO faults, quarantines them, and is then
resumed must end in exactly the fault-free final state — same label
stacks, same feature tables.  That is the contract that makes quarantine
safe to enable by default.

Marked ``chaos`` (registered in pyproject); the suite stays fast enough
to live inside the tier-1 gate.
"""

import numpy as np
import pytest

from test_resilience import dummy_description, fast_resilience
from test_workflow import make_description, source_dir, synth_site_image  # noqa: F401 — fixture re-export

from tmlibrary_tpu import faults
from tmlibrary_tpu.models.experiment import Experiment
from tmlibrary_tpu.models.store import ExperimentStore
from tmlibrary_tpu.resilience import DeviceHealthGuard, RetryPolicy
from tmlibrary_tpu.workflow.engine import Workflow

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _make_store(tmp_path, name):
    placeholder = Experiment(
        name=name, plates=[], channels=[], site_height=1, site_width=1
    )
    return ExperimentStore.create(tmp_path / name, placeholder)


def _chaos_description(source_dir, store):
    """The canonical test workflow with jterator re-batched to 4 batches
    of 4 sites, so two quarantines sit exactly at the 0.5 budget."""
    desc = make_description(source_dir, store)
    for stage in desc.stages:
        for step in stage.steps:
            if step.name == "jterator":
                step.args["batch_size"] = 4
    return desc


def test_faulted_run_plus_resume_converges(tmp_path, source_dir):
    """Device loss on jterator batch 1 and an IO fault on batch 3 (both
    outlasting every retry) quarantine those batches; clearing the fault
    plan and resuming must reproduce the fault-free run bit-for-bit."""
    ref = _make_store(tmp_path, "reference")
    Workflow(ref, _chaos_description(source_dir, ref),
             resilience=fast_resilience()).run()
    ref_labels = ref.read_labels(None, "nuclei")
    ref_feats = ref.read_features("nuclei")

    chaotic = _make_store(tmp_path, "chaotic")
    faults.install(faults.FaultPlan([
        faults.FaultSpec(site="batch_run", kind="device_loss",
                         step="jterator", batch=1, times=99),
        faults.FaultSpec(site="batch_run", kind="io_error",
                         step="jterator", batch=3, times=99),
    ], seed=7))
    res = fast_resilience(max_batch_failures=0.5, attempts=2)
    summary = Workflow(chaotic, _chaos_description(source_dir, chaotic),
                       resilience=res).run()
    # 4 jterator batches, budget floor(0.5 * 4) = 2: the run survives
    assert summary["jterator"]["quarantined"] == [1, 3]
    ledger = Workflow(chaotic, _chaos_description(source_dir, chaotic),
                      resilience=res).ledger
    failures = {e["batch"]: e for e in ledger.events()
                if e.get("event") == "batch_failed"}
    assert failures[1]["exception"] == "TransientDeviceError"
    assert failures[3]["exception"] == "OSError"
    assert faults.active().fire_counts() == {
        "batch_run/device_loss": 2,  # attempts=2: first try + one retry
        "batch_run/io_error": 2,
    }

    # the faults clear (relay back, disk back) — resume converges
    faults.clear()
    summary = Workflow(chaotic, _chaos_description(source_dir, chaotic),
                       resilience=res).run(resume=True)
    assert "quarantined" not in summary["jterator"]

    assert np.array_equal(chaotic.read_labels(None, "nuclei"), ref_labels)
    key = ["site_index", "label"]
    got = chaotic.read_features("nuclei").sort_values(key).reset_index(drop=True)
    want = ref_feats.sort_values(key).reset_index(drop=True)
    import pandas.testing

    pandas.testing.assert_frame_equal(got, want)


def test_down_relay_probe_degrades_instead_of_hanging(tmp_path):
    """A down TPU relay makes the device probe *hang*, not error.  The
    guard's timeout converts the hang into breaker failures; the breaker
    trips, the run degrades to CPU with a ``backend_degraded`` ledger
    event, and the workflow still finishes — the pre-resilience behavior
    was an indefinite hang."""
    import test_resilience  # registers the dummy step  # noqa: F401

    faults.install(faults.FaultPlan([
        faults.FaultSpec(site="device_probe", kind="hang", seconds=3.0,
                         times=99),
    ]))
    res = fast_resilience()
    # default probe (jax.devices() behind the fault hook), short deadline
    res.guard = DeviceHealthGuard(timeout=0.05, failure_threshold=1,
                                  cooldown=3600.0)
    store = _make_store(tmp_path, "relaydown")
    summary = Workflow(store, dummy_description(), resilience=res).run()
    assert summary["chaosdummy"]["n_batches"] == 4
    assert store.workflow_dir.joinpath("ledger.jsonl").exists()
    ev = Workflow(store, dummy_description(), resilience=res) \
        .ledger.degraded_backend()
    assert ev is not None and ev["backend"] == "cpu" and ev["where"] == "run"
    assert res.guard.degraded


def test_pipelined_quarantine_resume_converges(tmp_path, source_dir,
                                               monkeypatch):
    """Depth > 1 does not weaken the fault model: with ``TMX_FAULT_PLAN``
    armed the engine forces the sequential path (injected faults must
    land before a batch persists), quarantines the faulted batches, and
    a resume at ``pipeline_depth=4`` — now genuinely pipelined — still
    converges bit-for-bit to the fault-free reference."""
    ref = _make_store(tmp_path, "pipe_reference")
    Workflow(ref, _chaos_description(source_dir, ref),
             resilience=fast_resilience()).run()
    ref_labels = ref.read_labels(None, "nuclei")
    ref_feats = ref.read_features("nuclei")

    plan_file = tmp_path / "pipe_plan.json"
    plan_file.write_text(
        '{"seed": 11, "faults": ['
        '{"site": "batch_run", "kind": "device_loss",'
        ' "step": "jterator", "batch": 1, "times": 99},'
        '{"site": "batch_run", "kind": "io_error",'
        ' "step": "jterator", "batch": 3, "times": 99}]}'
    )
    monkeypatch.setenv("TMX_FAULT_PLAN", str(plan_file))
    faults._ENV_CHECKED = False  # re-arm the lazy env check
    assert faults.active() is not None

    chaotic = _make_store(tmp_path, "pipe_chaotic")
    res = fast_resilience(max_batch_failures=0.5, attempts=2)
    summary = Workflow(chaotic, _chaos_description(source_dir, chaotic),
                       resilience=res, pipeline_depth=4).run()
    assert summary["jterator"]["quarantined"] == [1, 3]
    wf = Workflow(chaotic, _chaos_description(source_dir, chaotic),
                  resilience=res, pipeline_depth=4)
    partial = [e for e in wf.ledger.events()
               if e.get("event") == "step_partial"
               and e.get("step") == "jterator"]
    # the armed plan forced the sequential path: no executor, no stats
    assert partial and "pipeline_stats" not in partial[0]

    # faults clear (relay back): resume runs the quarantined batches
    # through the REAL pipelined executor at depth 4 and converges
    monkeypatch.delenv("TMX_FAULT_PLAN")
    faults.clear()
    summary = wf.run(resume=True)
    assert "quarantined" not in summary["jterator"]
    done = [e for e in wf.ledger.events()
            if e.get("event") == "step_done" and e.get("step") == "jterator"]
    assert done and done[-1]["pipeline_stats"]["depth"] == 4
    assert done[-1]["pipeline_stats"]["source"] == "cli"

    assert np.array_equal(chaotic.read_labels(None, "nuclei"), ref_labels)
    key = ["site_index", "label"]
    got = chaotic.read_features("nuclei").sort_values(key).reset_index(drop=True)
    want = ref_feats.sort_values(key).reset_index(drop=True)
    import pandas.testing

    pandas.testing.assert_frame_equal(got, want)


def test_fault_plan_env_activation(tmp_path, monkeypatch):
    """``TMX_FAULT_PLAN`` arms the harness without code changes — the
    path ``scripts/chaos_run.py`` and operators use."""
    plan_file = tmp_path / "plan.json"
    plan_file.write_text(
        '{"seed": 3, "faults": [{"site": "batch_run", "kind": "device_loss",'
        ' "step": "chaosdummy", "batch": 0, "times": 99}]}'
    )
    monkeypatch.setenv("TMX_FAULT_PLAN", str(plan_file))
    # reset the lazy env check that clear() disarmed
    faults._ENV_CHECKED = False
    plan = faults.active()
    assert plan is not None and plan.seed == 3
    assert plan.specs[0].step == "chaosdummy"

    store = _make_store(tmp_path, "envplan")
    summary = Workflow(store, dummy_description(),
                       resilience=fast_resilience()).run()
    assert summary["chaosdummy"]["quarantined"] == [0]
