"""First-party DeltaVision ``.dv``/``.r3d`` container support (the
MRC-variant stack format of GE/Applied Precision widefield scopes).

Fixtures are written by ``write_dv`` below: the 1024-byte fixed header
(dims at 0, mode at 12, extended-header size at 92, DVID magic at 96,
NumTimes/ImgSequence/NumWaves shorts at 180/182/196) followed by the
extended header and row-major section planes in the declared interleave
order.
"""
import struct

import numpy as np
import pytest

from tmlibrary_tpu.errors import MetadataError
from tmlibrary_tpu.readers import DVReader


def write_dv(path, planes, sequence=0, byte_order="<", mode=6,
             ext_size=96, declare_sections=None):
    """``planes``: (W, Z, T, H, W) uint16-ish array indexed [c][z][t]."""
    n_w, n_z, n_t, h, w = planes.shape
    nsec = declare_sections if declare_sections is not None else n_w * n_z * n_t
    header = bytearray(1024)
    struct.pack_into(f"{byte_order}4i", header, 0, w, h, nsec, mode)
    struct.pack_into(f"{byte_order}i", header, 92, ext_size)
    struct.pack_into(f"{byte_order}h", header, 96, -16224)
    struct.pack_into(f"{byte_order}h", header, 180, n_t)
    struct.pack_into(f"{byte_order}h", header, 182, sequence)
    struct.pack_into(f"{byte_order}h", header, 196, n_w)
    dtype = np.dtype(byte_order + {0: "u1", 1: "i2", 2: "f4", 6: "u2"}[mode])

    def section_index(z, c, t):
        if sequence == 0:  # ZTW
            return (c * n_t + t) * n_z + z
        if sequence == 1:  # WZT
            return (t * n_z + z) * n_w + c
        return (t * n_w + c) * n_z + z  # ZWT

    sections = [None] * (n_w * n_z * n_t)
    for c in range(n_w):
        for z in range(n_z):
            for t in range(n_t):
                sections[section_index(z, c, t)] = planes[c, z, t]
    blob = bytearray(header) + bytearray(ext_size)
    for sec in sections:
        blob += np.ascontiguousarray(sec, dtype).tobytes()
    path.write_bytes(bytes(blob))


@pytest.fixture
def planes():
    rng = np.random.default_rng(7)
    return rng.integers(0, 60000, (2, 3, 2, 16, 20), dtype=np.uint16)


@pytest.mark.parametrize("sequence", [0, 1, 2])
@pytest.mark.parametrize("byte_order", ["<", ">"])
def test_dv_reader_all_orders(tmp_path, planes, sequence, byte_order):
    path = tmp_path / "s.dv"
    write_dv(path, planes, sequence=sequence, byte_order=byte_order)
    with DVReader(path) as r:
        assert (r.width, r.height) == (20, 16)
        assert (r.n_channels, r.n_zplanes, r.n_tpoints) == (2, 3, 2)
        for c in range(2):
            for z in range(3):
                for t in range(2):
                    np.testing.assert_array_equal(
                        r.read_plane(z, c, t), planes[c, z, t]
                    )
                    page = (c * 3 + z) * 2 + t
                    np.testing.assert_array_equal(
                        r.read_plane_linear(page), planes[c, z, t]
                    )


def test_dv_float_mode_and_int16(tmp_path):
    rng = np.random.default_rng(3)
    f = rng.random((1, 1, 1, 8, 8)).astype(np.float32)
    path = tmp_path / "f.dv"
    write_dv(path, f, mode=2)
    with DVReader(path) as r:
        np.testing.assert_array_equal(r.read_plane(0, 0, 0), f[0, 0, 0])
    # int16 with NEGATIVE values (deconvolved DV output routinely has
    # them): clipped at 0, never wrapped to ~65535
    i = rng.integers(-500, 3000, (1, 2, 1, 8, 8)).astype(np.int16)
    i[0, 1, 0, 0, 0] = -10
    path2 = tmp_path / "i.r3d"
    write_dv(path2, i, mode=1)
    with DVReader(path2) as r:
        out = r.read_plane(1, 0, 0)
        assert out.dtype == np.uint16
        np.testing.assert_array_equal(out, np.clip(i[0, 1, 0], 0, None))
        assert out[0, 0] == 0


def test_dv_rejects_bad_files(tmp_path, planes):
    p = tmp_path / "bad.dv"
    p.write_bytes(b"\0" * 500)  # short header
    with pytest.raises(MetadataError):
        DVReader(p).__enter__()
    p2 = tmp_path / "nomagic.dv"
    p2.write_bytes(b"\0" * 2048)
    with pytest.raises(MetadataError):
        DVReader(p2).__enter__()
    good = tmp_path / "good.dv"
    write_dv(good, planes)
    blob = good.read_bytes()
    trunc = tmp_path / "trunc.dv"
    trunc.write_bytes(blob[: len(blob) // 2])
    with pytest.raises(MetadataError):
        DVReader(trunc).__enter__()
    nofactor = tmp_path / "nofactor.dv"
    write_dv(nofactor, planes, declare_sections=11)
    with pytest.raises(MetadataError):
        DVReader(nofactor).__enter__()


def test_dv_ingest_end_to_end(tmp_path, planes):
    """Per-well .dv stacks -> metaconfig (auto) -> imextract -> pixels in
    the canonical store, bit-identical, Z/T preserved."""
    from tmlibrary_tpu.models.experiment import Experiment
    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.workflow.registry import get_step

    rng = np.random.default_rng(11)
    src = tmp_path / "source"
    src.mkdir()
    data = {}
    for well in ("A01", "B02"):
        stack = rng.integers(0, 60000, (2, 3, 2, 16, 20), dtype=np.uint16)
        write_dv(src / f"exp_{well}.dv", stack)
        data[well] = stack

    root = tmp_path / "exp"
    store = ExperimentStore.create(
        root, Experiment(name="dvtest", plates=[], channels=[],
                         site_height=1, site_width=1))
    meta = get_step("metaconfig")(store)
    meta.init({"source_dir": str(src), "handler": "auto"})
    result = meta.run(0)
    assert result["n_files"] == 2 * 2 * 3 * 2  # wells x C x Z x T

    exp = ExperimentStore.open(root).experiment
    assert exp.n_sites == 2
    assert exp.n_zplanes == 3 and exp.n_tpoints == 2
    assert {c.name for c in exp.channels} == {"C00", "C01"}
    rows_cols = {(w.row, w.column) for p in exp.plates for w in p.wells}
    assert rows_cols == {(0, 0), (1, 1)}

    ime = get_step("imextract")(store)
    ime.init({})
    for j in ime.list_batches():
        ime.run(j)

    store = ExperimentStore.open(root)
    for c in range(2):
        for z in range(3):
            for t in range(2):
                px = store.read_sites(None, channel=c, tpoint=t, zplane=z)
                np.testing.assert_array_equal(px[0], data["A01"][c, z, t])
                np.testing.assert_array_equal(px[1], data["B02"][c, z, t])


def test_dv_handler_skips_unreadable(tmp_path, planes):
    from tmlibrary_tpu.workflow.steps.vendors import dv_sidecar

    src = tmp_path / "source"
    src.mkdir()
    write_dv(src / "ok_A01.dv", planes)
    (src / "bad_B01.dv").write_bytes(b"\0" * 2048)
    entries, skipped = dv_sidecar(src)
    assert skipped == 1
    assert {e["well_row"] for e in entries} == {0}
    assert len(entries) == 2 * 3 * 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert dv_sidecar(empty) is None
