"""Data-quality & numerics observability (``tmlibrary_tpu.qc``).

Pins the subsystem's hard invariants:

- pipeline outputs are bit-identical with QC on or off (QC only reads);
- disabled QC hands out the shared null session (one attribute lookup
  and a no-op call per instrumentation point);
- P² sketch quantiles track ``np.percentile`` and merge across hosts
  with the ``merge_snapshots`` discipline;
- a QC-on workflow run writes ``workflow/qc.json``, appends
  ``qc_batch``/``qc_site`` ledger events and mirrors ``tmx_qc_*``
  registry series — and flags never fail the run;
- ``registry_from_ledger`` rebuilds the QC gauges post-hoc, tolerates
  unknown event kinds (warn once, never raise), and ``tmx metrics
  --merge`` carries ``tmx_qc_*`` across a 2-host fleet;
- the drift sentinel's exit codes are pinned: 0 ok · 1 drift · 2 stale
  · 3 no reference.
"""

import json
import logging
import time

import numpy as np
import pytest

from tmlibrary_tpu import qc, telemetry
from tmlibrary_tpu.ops import qc as qc_ops

from test_workflow import (  # noqa: F401  (fixtures)
    make_description,
    source_dir,
    store,
    synth_site_image,
)


# ------------------------------------------------------------- P² sketches
def test_p2_quantile_tracks_numpy_percentile():
    rng = np.random.default_rng(7)
    values = rng.normal(100.0, 15.0, 5000)
    p50, p95 = qc.P2Quantile(0.50), qc.P2Quantile(0.95)
    for v in values:
        p50.update(v)
        p95.update(v)
    assert p50.value() == pytest.approx(np.percentile(values, 50), abs=1.5)
    assert p95.value() == pytest.approx(np.percentile(values, 95), abs=2.5)


def test_p2_quantile_exact_below_five_observations():
    p = qc.P2Quantile(0.50)
    assert np.isnan(p.value())
    for v in (3.0, 1.0, 2.0):
        p.update(v)
    assert p.value() == 2.0  # exact interpolation over the sorted sample


def test_feature_sketch_counts_exact_and_nan_tallies():
    s = qc.FeatureSketch()
    n_nan, n_inf = s.update(np.array([1.0, np.nan, 3.0, np.inf, -np.inf]))
    assert (n_nan, n_inf) == (1, 2)
    d = s.to_dict()
    assert d["count"] == 2 and d["min"] == 1.0 and d["max"] == 3.0
    assert d["nan"] == 1 and d["inf"] == 2
    assert d["mean"] == pytest.approx(2.0)


def test_feature_sketch_empty_serializes_none():
    d = qc.FeatureSketch().to_dict()
    assert d["count"] == 0
    assert d["min"] is None and d["max"] is None
    assert d["p50"] is None and d["p95"] is None


def test_merge_sketch_dicts_discipline():
    a, b = qc.FeatureSketch(), qc.FeatureSketch()
    a.update(np.arange(100, dtype=np.float64))
    b.update(np.arange(1000, 1010, dtype=np.float64))
    da, db = a.to_dict(), b.to_dict()
    m = qc.merge_sketch_dicts(da, db)
    # counts/sums add, min/max fold
    assert m["count"] == 110
    assert m["min"] == 0.0 and m["max"] == 1009.0
    assert m["sum"] == pytest.approx(da["sum"] + db["sum"])
    # quantiles follow the LARGER sample (a has 100 >> b's 10)
    assert m["p50"] == da["p50"] and m["p95"] == da["p95"]
    # ties keep the first argument
    t = qc.merge_sketch_dicts(da, da)
    assert t["p50"] == da["p50"]


def test_merge_of_one_is_identity():
    """Satellite edge case: a single-host run merged through the same
    path as a fleet run must not change any sketch value."""
    s = qc.FeatureSketch()
    s.update(np.linspace(0.0, 50.0, 77))
    d = s.to_dict()
    profile = {"schema_version": qc.QC_SCHEMA_VERSION,
               "written_at_unix": 123.0,
               "steps": {"jterator": {"batches": 2, "sites": 8,
                                      "flagged": 0}},
               "channels": {"DAPI": {"focus_tenengrad": {
                   "min": 1.0, "max": 2.0, "mean": 1.5, "count": 8}}},
               "illumination": {}, "features": {"nuclei.area": d},
               "guards": {"nan_columns": [], "nan_values": 0,
                          "inf_values": 0, "count_z_max": 0.0,
                          "capacity_saturated_batches": 0},
               "worst_sites": [], "flagged": [], "flagged_total": 0}
    merged = qc.merge_profiles([("host0", profile)])
    assert merged["features"]["nuclei.area"] == d
    assert merged["steps"] == profile["steps"]
    assert merged["channels"]["DAPI"]["focus_tenengrad"]["min"] == 1.0
    assert merged["hosts"] == ["host0"]


# -------------------------------------------------------- on-device stats
def test_saturation_fraction_all_saturated_channel():
    img = np.full((32, 32), 65535.0, np.float32)
    assert float(qc_ops.saturation_fraction(img)) == 1.0
    assert float(qc_ops.saturation_fraction(img * 0.0)) == 0.0


def test_focus_metrics_flat_image_near_zero_and_rank_sharpness():
    flat = np.full((64, 64), 500.0, np.float32)
    assert float(qc_ops.focus_tenengrad(flat)) == pytest.approx(0.0)
    assert float(qc_ops.laplacian_variance(flat)) == pytest.approx(0.0)
    rng = np.random.default_rng(3)
    sharp = synth_site_image(rng).astype(np.float32)
    # crude blur: 2x2 box mean, applied twice
    blurred = sharp.copy()
    for _ in range(2):
        blurred = (blurred + np.roll(blurred, 1, 0) + np.roll(blurred, 1, 1)
                   + np.roll(np.roll(blurred, 1, 0), 1, 1)) / 4.0
    assert float(qc_ops.focus_tenengrad(sharp)) > float(
        qc_ops.focus_tenengrad(blurred))
    assert float(qc_ops.laplacian_variance(sharp)) > float(
        qc_ops.laplacian_variance(blurred))


def test_background_level_is_darkest_tile_mean():
    img = np.full((64, 64), 1000.0, np.float32)
    img[:8, :8] = 100.0  # one dark 8x8 corner tile
    assert float(qc_ops.background_level(img)) == pytest.approx(100.0)
    # degrades to the global mean when smaller than one tile
    tiny = np.full((4, 4), 7.0, np.float32)
    assert float(qc_ops.background_level(tiny)) == pytest.approx(7.0)


# ------------------------------------------------------ gate + null session
def test_disabled_qc_hands_out_shared_null_session(monkeypatch):
    monkeypatch.delenv("TMX_QC", raising=False)
    qc.set_enabled(None)
    assert not qc.enabled()
    s = qc.get_session()
    assert s is qc._NULL_SESSION
    assert s is qc.get_session()  # shared, not allocated per call
    assert s.observe_batch("jterator", [0, 1]) is None
    assert s.observe_illumination("DAPI", [50], [300.0]) is None
    assert s.snapshot() == {}
    assert qc.record_summary() is None


def test_enabled_resolution_override_beats_env(monkeypatch):
    monkeypatch.setenv("TMX_QC", "0")
    qc.set_enabled(True)
    assert qc.enabled()
    qc.set_enabled(None)
    assert not qc.enabled()
    monkeypatch.setenv("TMX_QC", "1")
    assert qc.enabled()


def test_cached_batch_fn_keys_on_qc_gate():
    from tmlibrary_tpu.benchmarks import smooth_threshold_description
    from tmlibrary_tpu.jterator import pipeline as jp

    jp._BATCH_FN_CACHE.clear()
    off = jp.cached_batch_fn(smooth_threshold_description(), 64, qc=False)
    on = jp.cached_batch_fn(smooth_threshold_description(), 64, qc=True)
    assert off is not on
    assert off is jp.cached_batch_fn(smooth_threshold_description(), 64,
                                     qc=False)
    # qc=None resolves the live gate onto the same keys
    qc.set_enabled(True)
    assert on is jp.cached_batch_fn(smooth_threshold_description(), 64)
    qc.set_enabled(False)
    assert off is jp.cached_batch_fn(smooth_threshold_description(), 64)
    jp._BATCH_FN_CACHE.clear()


def test_perf_wrapper_never_reuses_executable_across_qc_gate():
    """Regression: perf's AOT executable cache keys on the program
    digest — a QC-off run compiling first (same description, window,
    capacity, strategy, shapes) must NOT hand its executable to the
    QC-on wrapper, which expects a (SiteResult, qc_stats) pytree back.
    Order-dependent in the full suite (any engine run before a QC-on
    one), deterministic here."""
    from tmlibrary_tpu import perf, telemetry
    from tmlibrary_tpu.benchmarks import smooth_threshold_description
    from tmlibrary_tpu.jterator import pipeline as jp

    jp._BATCH_FN_CACHE.clear()
    jp._WRAPPED_FN_CACHE.clear()
    telemetry.reset_registry(enabled=True)
    perf.reset_profiles()
    try:
        import jax.numpy as jnp

        raw = {"DAPI": jnp.asarray(
            np.random.default_rng(0).integers(
                0, 4000, (2, 64, 64)).astype(np.uint16))}
        shifts = jnp.zeros((2, 2), jnp.int32)
        off = jp.cached_batch_fn(smooth_threshold_description(), 16,
                                 qc=False)
        assert not isinstance(off(raw, {}, shifts), tuple)
        on = jp.cached_batch_fn(smooth_threshold_description(), 16,
                                qc=True)
        out = on(raw, {}, shifts)
        assert isinstance(out, tuple)
        result, qc_stats = out
        assert set(qc_stats) == {"DAPI"}
        assert "saturation_frac" in qc_stats["DAPI"]
    finally:
        jp._BATCH_FN_CACHE.clear()
        jp._WRAPPED_FN_CACHE.clear()
        perf.reset_profiles()
        telemetry.reset_registry()


# ------------------------------------------------------- observe_batch
def _image_stats(n, focus=None, sat=None, background=None):
    return {"DAPI": {
        "saturation_frac": np.full(n, 0.0 if sat is None else sat),
        "background": np.full(n, 300.0 if background is None
                              else background),
        "focus_tenengrad": np.full(n, 10.0 if focus is None else focus),
        "laplacian_var": np.full(n, 0.05),
    }}


def test_observe_batch_zero_object_sites():
    """Satellite edge case: noise-only sites with zero objects must fold
    cleanly — no flags, no NaN tallies, empty sketches stay empty."""
    qc.set_enabled(True)
    qc.reset_session()
    s = qc.get_session()
    summary = s.observe_batch(
        "jterator", [0, 1, 2, 3],
        image_stats=_image_stats(4),
        counts={"nuclei": np.zeros(4, np.int32)},
        measurements={"nuclei": {
            # all-padding rows: every value masked out by count=0
            "Intensity_mean_DAPI": np.full((4, 8), np.nan),
        }},
    )
    assert summary["flagged_sites"] == []
    assert summary["nan_values"] == 0 and summary["nan_columns"] == 0
    snap = s.snapshot()
    assert snap["features"]["nuclei.Intensity_mean_DAPI"]["count"] == 0
    assert snap["guards"]["nan_columns"] == []
    assert snap["steps"]["jterator"]["sites"] == 4


def test_observe_batch_flags_saturated_sites_and_masks_padding():
    qc.set_enabled(True)
    qc.reset_session()
    s = qc.get_session()
    sat = np.array([0.0, 0.9, 0.0, 1.0])
    stats = _image_stats(4)
    stats["DAPI"]["saturation_frac"] = sat
    meas = np.full((4, 8), np.nan)
    meas[:, :2] = 5.0  # two real objects per site, six padding rows
    summary = s.observe_batch(
        "jterator", [10, 11, 12, 13], image_stats=stats,
        counts={"nuclei": np.full(4, 2, np.int32)},
        measurements={"nuclei": {"Intensity_mean_DAPI": meas}},
    )
    flags = summary["flagged_sites"]
    assert [f["site"] for f in flags] == [11, 13]
    assert all(f["reason"] == "saturation" for f in flags)
    # padding NaNs were masked out, not counted as numerics faults
    assert summary["nan_values"] == 0
    assert s.snapshot()["features"]["nuclei.Intensity_mean_DAPI"][
        "count"] == 8
    # cumulative gauge fields + live registry mirror
    assert summary["flagged_total"] == 2
    telemetry.reset_registry(enabled=True)
    s.observe_batch("jterator", [14], image_stats=_image_stats(1),
                    counts={"nuclei": np.array([2], np.int32)})
    reg = telemetry.get_registry()
    assert reg.gauge("tmx_qc_worst_focus", channel="DAPI").value == 10.0
    assert reg.gauge("tmx_qc_max_saturation_frac",
                     channel="DAPI").value == 1.0


def test_observe_batch_nan_feature_columns_counted():
    qc.set_enabled(True)
    qc.reset_session()
    s = qc.get_session()
    summary = s.observe_batch(
        "jterator", [0, 1], image_stats=_image_stats(2),
        counts={"nuclei": np.full(2, 3, np.int32)},
        measurements={"nuclei": {
            "Texture_bad": np.array([[np.nan, 2.0, np.inf],
                                     [1.0, np.nan, 3.0]]),
            "Intensity_ok": np.ones((2, 3)),
        }},
    )
    assert summary["nan_values"] == 2 and summary["inf_values"] == 1
    assert summary["nan_columns"] == 1
    snap = s.snapshot()
    assert snap["guards"]["nan_columns"] == ["nuclei.Texture_bad"]
    assert qc.record_summary()["nan_columns"] == 1


def test_capacity_saturation_flag_reused_as_guard():
    qc.set_enabled(True)
    qc.reset_session()
    s = qc.get_session()
    summary = s.observe_batch("jterator", [0],
                              image_stats=_image_stats(1), saturated=True)
    assert summary["capacity_saturated"]
    assert s.snapshot()["guards"]["capacity_saturated_batches"] == 1


# ------------------------------------------- bit-identity (the hard pin)
def _read_features_sorted(st, name):
    return (st.read_features(name)
            .sort_values(["site_index", "label"])
            .reset_index(drop=True))


def test_jterator_bit_identical_with_qc_on_and_off(source_dir, store):
    """THE invariant that makes QC safe to ship enabled: the instrumented
    run persists exactly the same label stacks and feature tables — QC
    only reads batch inputs/outputs, never feeds back into them."""
    import pandas.testing

    from tmlibrary_tpu.workflow.registry import get_step

    desc = make_description(source_dir, store)
    for name in ("metaconfig", "imextract", "corilla"):
        sd = next(s for stage in desc.stages for s in stage.steps
                  if s.name == name)
        step = get_step(name)(store)
        step.init(sd.args)
        for j in step.list_batches():
            step.run(j)
    jd = next(s for stage in desc.stages for s in stage.steps
              if s.name == "jterator")

    qc.set_enabled(True)
    qc.reset_session()
    jt = get_step("jterator")(store)
    jt.init(jd.args)
    for j in jt.list_batches():
        jt.run(j)
    on_labels = store.read_labels(None, "nuclei").copy()
    on_feats = _read_features_sorted(store, "nuclei")
    # the QC-on run actually observed evidence
    snap = qc.get_session().snapshot()
    assert snap["steps"]["jterator"]["sites"] == 16
    assert "DAPI" in snap["channels"]
    assert snap["channels"]["DAPI"]["focus_tenengrad"]["count"] == 16
    assert any(k.startswith("nuclei.") for k in snap["features"])

    qc.set_enabled(False)
    qc.reset_session()
    jt2 = get_step("jterator")(store)
    jt2.delete_previous_output()
    jt2.init(jd.args)
    for j in jt2.list_batches():
        jt2.run(j)
    assert np.array_equal(store.read_labels(None, "nuclei"), on_labels)
    pandas.testing.assert_frame_equal(
        _read_features_sorted(store, "nuclei"), on_feats
    )


# ------------------------------------------- engine + workflow integration
def test_workflow_run_with_qc_writes_profile_and_ledger(source_dir, store):
    from tmlibrary_tpu.workflow.engine import RunLedger, Workflow

    qc.set_enabled(True)
    desc = make_description(source_dir, store)
    summary = Workflow(store, desc).run()
    assert summary["jterator"]["collected"]["objects_total"]["nuclei"] > 0

    # profile written next to the ledger (host0 convenience copy too)
    profile = json.loads((store.workflow_dir / "qc.json").read_text())
    assert profile["schema_version"] == qc.QC_SCHEMA_VERSION
    assert profile["steps"]["jterator"]["sites"] == 16
    assert profile["channels"]["DAPI"]["saturation_frac"]["max"] == 0.0
    assert profile["illumination"]["DAPI"]["p50"] > 0  # corilla hook
    feats = profile["features"]
    assert feats and all(v["nan"] == 0 for v in feats.values())

    # qc_batch events rode the engine thread into the ledger ...
    events = RunLedger(store.workflow_dir / "ledger.jsonl").events()
    qc_batches = [e for e in events if e.get("event") == "qc_batch"]
    assert len(qc_batches) == 2  # batch_size=8 over 16 sites
    assert all("flagged_sites" not in (e.get("summary") or {})
               for e in qc_batches)
    # ... and registry_from_ledger rebuilds the QC gauges post-hoc
    reg = telemetry.registry_from_ledger(events)
    snap = reg.snapshot()
    focus = [g for g in snap["gauges"]
             if g["name"] == "tmx_qc_worst_focus"]
    assert focus and focus[0]["labels"]["channel"] == "DAPI"
    live = telemetry.get_registry()
    assert live.gauge("tmx_qc_worst_focus",
                      channel="DAPI").value == pytest.approx(
        focus[0]["value"])
    # `tmx qc` renders from these artifacts and exits 3 (no reference)
    from tmlibrary_tpu.cli import main

    assert main(["qc", "--root", str(store.root)]) == qc.EXIT_NO_REFERENCE


def test_workflow_run_without_qc_writes_nothing(source_dir, store):
    from tmlibrary_tpu.workflow.engine import RunLedger, Workflow

    qc.set_enabled(False)
    desc = make_description(source_dir, store)
    Workflow(store, desc).run()
    assert not (store.workflow_dir / "qc.json").exists()
    assert not list(store.workflow_dir.glob("qc.*.json"))
    events = RunLedger(store.workflow_dir / "ledger.jsonl").events()
    assert not [e for e in events if str(e.get("event", "")
                                         ).startswith("qc")]


def test_note_qc_flags_sites_without_failing(tmp_path):
    """QC flags are ledger evidence, never control flow: _note_qc appends
    qc_batch + per-site qc_site events and the step keeps running."""
    from tmlibrary_tpu.workflow.engine import RunLedger, Workflow

    ledger = RunLedger(tmp_path / "ledger.jsonl", host="host0")
    wf = Workflow.__new__(Workflow)
    wf.ledger = ledger
    flagged = [{"site": 3, "step": "jterator", "channel": "DAPI",
                "reason": "saturation", "value": 0.9}]
    n = wf._note_qc("jterator", 0, {"qc": {
        "channels": {"DAPI": {"focus_min": 2.0}},
        "worst_focus": 2.0, "nan_columns": 0, "nan_values": 0,
        "inf_values": 0, "count_z_max": 0.0, "flagged_total": 1,
        "flagged_sites": flagged, "capacity_saturated": False,
    }})
    assert n == 1
    events = ledger.events()
    kinds = [e["event"] for e in events]
    assert kinds == ["qc_batch", "qc_site"]
    site_ev = events[1]
    assert site_ev["site"] == 3 and site_ev["reason"] == "saturation"
    assert site_ev["step"] == "jterator"  # once — from ledger.append
    # results without QC evidence are a no-op
    assert wf._note_qc("jterator", 1, {"n_sites": 8}) == 0
    assert wf._note_qc("jterator", 2, None) == 0


# ------------------------------------------------ multi-host fleet paths
def _qc_batch_event(host, focus, ts):
    return {"event": "qc_batch", "step": "jterator", "batch": 0,
            "ts": ts, "host": host,
            "summary": {"channels": {"DAPI": {"focus_min": focus,
                                              "saturation_max": 0.1,
                                              "background_mean": 300.0}},
                        "worst_focus": focus, "nan_columns": 1,
                        "nan_values": 2, "inf_values": 0,
                        "count_z_max": 1.5, "flagged_total": 1}}


def test_registry_from_ledger_two_host_qc_attribution(tmp_path):
    events = [
        {"event": "run_started", "ts": 1.0, "host": "host0"},
        _qc_batch_event("host0", 4.0, 2.0),
        _qc_batch_event("host1", 9.0, 2.5),
        {"event": "qc_site", "step": "jterator", "batch": 0, "site": 7,
         "reason": "focus", "ts": 3.0, "host": "host1"},
    ]
    snap = telemetry.registry_from_ledger(events).snapshot()
    focus = {g["labels"]["host"]: g["value"] for g in snap["gauges"]
             if g["name"] == "tmx_qc_worst_focus"}
    assert focus == {"host0": 4.0, "host1": 9.0}
    flagged = [c for c in snap["counters"]
               if c["name"] == "tmx_qc_sites_flagged_total"]
    assert len(flagged) == 1 and flagged[0]["labels"]["host"] == "host1"
    nan_bad = [c for c in snap["counters"]
               if c["name"] == "tmx_qc_nan_values_total"]
    assert {c["labels"]["host"] for c in nan_bad} == {"host0", "host1"}

    # the same 2-host ledger renders one fleet view end to end through
    # `tmx metrics --merge` (per-host ledger-derived snapshots on disk)
    from tmlibrary_tpu.cli import main

    wf = tmp_path / "run" / "workflow"
    wf.mkdir(parents=True)
    with (wf / "ledger.jsonl").open("w") as fh:
        for ev in events:
            fh.write(json.dumps(ev) + "\n")
    for host in ("host0", "host1"):
        per_host = [e for e in events if e.get("host") == host]
        (wf / f"metrics.{host}.json").write_text(telemetry.render_json(
            telemetry.registry_from_ledger(per_host).snapshot()))
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert main(["metrics", "--merge", str(tmp_path / "run")]) == 0
    prom = buf.getvalue()
    assert "tmx_qc_worst_focus" in prom
    assert 'host="host0"' in prom and 'host="host1"' in prom


def test_registry_from_ledger_unknown_kind_warns_once(caplog):
    """Satellite forward-compat pin: an old checkout must keep deriving
    metrics from a newer writer's ledger — unknown kinds warn once per
    kind and are otherwise ignored."""
    events = [
        {"event": "run_started", "ts": 1.0},
        {"event": "hologram_calibrated", "ts": 2.0, "step": "jterator"},
        {"event": "hologram_calibrated", "ts": 3.0, "step": "jterator"},
        {"event": "batch_done", "step": "jterator", "batch": 0,
         "elapsed": 1.0, "ts": 4.0, "result": {"n_sites": 8}},
    ]
    with caplog.at_level(logging.WARNING,
                         logger="tmlibrary_tpu.telemetry"):
        snap = telemetry.registry_from_ledger(events).snapshot()
    warned = [r for r in caplog.records
              if "hologram_calibrated" in r.getMessage()]
    assert len(warned) == 1
    # the known events still derived
    assert any(c["name"] == "tmx_batches_done_total"
               for c in snap["counters"])


def test_profile_roundtrip_and_host_merge(tmp_path):
    qc.set_enabled(True)
    qc.reset_session()
    s = qc.get_session()
    s.observe_batch("jterator", [0, 1], image_stats=_image_stats(2),
                    counts={"nuclei": np.array([2, 3], np.int32)},
                    measurements={"nuclei": {
                        "area": np.array([[4.0, 5.0, 0.0],
                                          [6.0, 7.0, 8.0]])}})
    prof0 = s.snapshot()
    qc.write_profile(tmp_path / "qc.host0.json", prof0)
    prof1 = json.loads(json.dumps(prof0, default=float))
    prof1["host"] = "host1"
    prof1["features"]["nuclei.area"]["max"] = 99.0
    qc.write_profile(tmp_path / "qc.host1.json", prof1)
    pairs = qc.load_run_profiles(tmp_path)
    assert [h for h, _ in pairs] == ["host0", "host1"]
    merged = qc.merge_profiles(pairs)
    area = merged["features"]["nuclei.area"]
    assert area["count"] == 10 and area["max"] == 99.0
    assert merged["steps"]["jterator"]["sites"] == 4


# --------------------------------------------------------- drift sentinel
def _profile_with_feature(p50, p95=None, nan=0, written=None, sat=0.0):
    return {
        "schema_version": qc.QC_SCHEMA_VERSION,
        "written_at_unix": time.time() if written is None else written,
        "features": {"nuclei.area": {
            "count": 100, "sum": p50 * 100, "mean": p50, "min": 0.0,
            "max": p50 * 2, "nan": nan, "inf": 0, "p50": p50,
            "p95": p50 * 1.2 if p95 is None else p95}},
        "channels": {"DAPI": {"saturation_frac": {
            "min": 0.0, "max": sat, "mean": sat, "count": 100}}},
    }


def test_compare_profiles_exit_codes_pinned():
    cur = _profile_with_feature(100.0)
    ref = _profile_with_feature(100.0)
    # 3: no reference at all
    v = qc.compare_profiles(cur, None)
    assert (v["status"], v["exit_code"]) == ("no_reference", 3)
    # 0: within threshold
    v = qc.compare_profiles(cur, ref, threshold=0.25)
    assert (v["status"], v["exit_code"]) == ("ok", 0)
    assert v["checked"] == 2  # one feature + one channel saturation
    # 1: median shifted beyond threshold x spread
    v = qc.compare_profiles(_profile_with_feature(200.0), ref)
    assert (v["status"], v["exit_code"]) == ("drift", 1)
    assert v["drifted"][0]["kind"] == "median_shift"
    # 1: new NaNs where the reference had none
    v = qc.compare_profiles(_profile_with_feature(100.0, nan=3), ref)
    assert v["exit_code"] == 1
    assert any(d["kind"] == "new_nan" for d in v["drifted"])
    # 1: saturation rose > 0.25 absolute
    v = qc.compare_profiles(_profile_with_feature(100.0, sat=0.5), ref)
    assert v["exit_code"] == 1
    assert any(d["kind"] == "saturation" for d in v["drifted"])
    # 2: stale reference (only when a budget is set; default 0 = off)
    old = _profile_with_feature(100.0, written=time.time() - 48 * 3600)
    v = qc.compare_profiles(cur, old, stale_hours=24.0)
    assert (v["status"], v["exit_code"]) == ("stale", 2)
    assert v["age_hours"] == pytest.approx(48.0, abs=0.2)
    v = qc.compare_profiles(cur, old, stale_hours=0.0)
    assert v["exit_code"] == 0
    # drift outranks stale
    v = qc.compare_profiles(_profile_with_feature(200.0), old,
                            stale_hours=24.0)
    assert v["exit_code"] == 1


def test_cmd_qc_cli_exit_codes(store, tmp_path, monkeypatch, capsys):
    from tmlibrary_tpu.cli import main

    monkeypatch.chdir(tmp_path)  # no accidental tuning/QC_BASELINE.json
    monkeypatch.delenv("TMX_QC_BASELINE", raising=False)
    monkeypatch.delenv("TMX_QC_STALE_HOURS", raising=False)

    # no QC evidence at all: generic failure (1), not a pinned verdict
    assert main(["qc", "--root", str(store.root)]) == 1
    assert "no QC evidence" in capsys.readouterr().err

    profile = _profile_with_feature(100.0)
    profile["steps"] = {"jterator": {"batches": 2, "sites": 16,
                                     "flagged": 0}}
    (store.workflow_dir / "qc.json").write_text(
        json.dumps(profile, default=float))
    # 3: evidence but no reference
    assert main(["qc", "--root", str(store.root)]) == 3
    # 0: reference == own profile
    ref = tmp_path / "ref.json"
    ref.write_text(json.dumps(profile, default=float))
    assert main(["qc", "--root", str(store.root),
                 "--reference", str(ref)]) == 0
    out = capsys.readouterr().out
    assert "drift verdict: ok" in out and "jterator" in out
    # reference also resolves via the TMX_QC_BASELINE env
    monkeypatch.setenv("TMX_QC_BASELINE", str(ref))
    assert main(["qc", "--root", str(store.root)]) == 0
    monkeypatch.delenv("TMX_QC_BASELINE")
    # 1: doctored reference median
    doctored = json.loads(ref.read_text())
    doctored["features"]["nuclei.area"]["p50"] = 500.0
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(doctored))
    assert main(["qc", "--root", str(store.root),
                 "--reference", str(bad)]) == 1
    assert "DRIFT" in capsys.readouterr().out
    # 2: old reference + a staleness budget
    stale = json.loads(ref.read_text())
    stale["written_at_unix"] = time.time() - 100 * 3600
    sp = tmp_path / "stale.json"
    sp.write_text(json.dumps(stale))
    assert main(["qc", "--root", str(store.root), "--reference", str(sp),
                 "--stale-hours", "24"]) == 2
    capsys.readouterr()
    # --json emits the machine view with the same verdict
    assert main(["qc", "--root", str(store.root), "--reference", str(ref),
                 "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["verdict"]["exit_code"] == 0
    assert payload["profile"]["steps"]["jterator"]["sites"] == 16


# ----------------------------------------------------------- tmx top / qc
def test_top_once_json_includes_qc(store, capsys):
    from tmlibrary_tpu.cli import main

    profile = _profile_with_feature(100.0)
    profile["flagged_total"] = 2
    profile["guards"] = {"nan_columns": ["nuclei.bad"], "nan_values": 1,
                         "inf_values": 0, "count_z_max": 0.0,
                         "capacity_saturated_batches": 0}
    (store.workflow_dir / "qc.json").write_text(
        json.dumps(profile, default=float))
    assert main(["top", "--root", str(store.root), "--once",
                 "--json"]) == 0
    view = json.loads(capsys.readouterr().out)
    assert view["qc"]["flagged_total"] == 2
    # and the text dashboard paints the QC row with the non-finite flag
    assert main(["top", "--root", str(store.root), "--once"]) == 0
    out = capsys.readouterr().out
    assert "qc: flagged 2" in out
    assert "NON-FINITE FEATURES" in out
