import numpy as np
import pandas as pd
import pytest

from tmlibrary_tpu.errors import NotSupportedError, RegistryError
from tmlibrary_tpu.models.experiment import grid_experiment
from tmlibrary_tpu.models.store import ExperimentStore
from tmlibrary_tpu.tools import ToolRequestManager, get_tool, list_tools


@pytest.fixture
def store_with_features(tmp_path, rng):
    """Store with a synthetic two-population feature table."""
    exp = grid_experiment(name="tools", well_rows=1, well_cols=1,
                          sites_per_well=(2, 2), site_shape=(16, 16))
    store = ExperimentStore.create(tmp_path / "exp", exp)
    rows = []
    for site in range(4):
        for label in range(1, 21):
            # population A: small dim objects; population B: large bright
            pop_b = label > 10
            rows.append(
                {
                    "site_index": site,
                    "plate": "plate00",
                    "well_row": 0,
                    "well_col": 0,
                    "site_y": site // 2,
                    "site_x": site % 2,
                    "label": label,
                    "Morphology_area": rng.normal(400 if pop_b else 80, 10),
                    "Intensity_mean_DAPI": rng.normal(3000 if pop_b else 500, 50),
                }
            )
    store.append_features("nuclei", pd.DataFrame(rows), shard="batch_000")
    return store


def test_registry():
    assert set(list_tools()) >= {"classification", "clustering", "heatmap"}
    with pytest.raises(RegistryError):
        get_tool("nope")


def test_clustering_separates_populations(store_with_features):
    mgr = ToolRequestManager(store_with_features)
    result = mgr.submit("clustering", {"objects_name": "nuclei", "k": 2})
    assert result.layer_type == "categorical"
    v = result.values
    a = v[v["label"] <= 10]["value"]
    b = v[v["label"] > 10]["value"]
    # each true population lands in one cluster
    assert a.nunique() == 1 and b.nunique() == 1
    assert a.iloc[0] != b.iloc[0]
    # result persisted
    results = mgr.list_results()
    assert len(results) == 1 and results[0]["tool"] == "clustering"


@pytest.mark.parametrize("method", ["logreg", "svm", "randomforest"])
def test_classification_methods(store_with_features, method):
    mgr = ToolRequestManager(store_with_features)
    examples = [
        {"site_index": 0, "label": 1, "class": "dim"},
        {"site_index": 0, "label": 2, "class": "dim"},
        {"site_index": 1, "label": 3, "class": "dim"},
        {"site_index": 0, "label": 11, "class": "bright"},
        {"site_index": 0, "label": 12, "class": "bright"},
        {"site_index": 1, "label": 13, "class": "bright"},
    ]
    result = mgr.submit(
        "classification",
        {"objects_name": "nuclei", "method": method, "training_examples": examples},
    )
    v = result.values
    classes = result.attributes["classes"]
    # population A (labels 1..10) should classify 'dim', B 'bright'
    pred_a = [classes[i] for i in v[v["label"] <= 10]["value"]]
    pred_b = [classes[i] for i in v[v["label"] > 10]["value"]]
    assert np.mean([p == "dim" for p in pred_a]) > 0.95
    assert np.mean([p == "bright" for p in pred_b]) > 0.95


def test_classification_requires_examples(store_with_features):
    mgr = ToolRequestManager(store_with_features)
    with pytest.raises(NotSupportedError):
        mgr.submit("classification", {"objects_name": "nuclei"})


def test_heatmap(store_with_features):
    mgr = ToolRequestManager(store_with_features)
    result = mgr.submit(
        "heatmap", {"objects_name": "nuclei", "feature": "Intensity_mean_DAPI"}
    )
    assert result.layer_type == "continuous"
    assert result.attributes["max"] > result.attributes["min"]
    assert len(result.values) == 80


def test_heatmap_unknown_feature(store_with_features):
    mgr = ToolRequestManager(store_with_features)
    with pytest.raises(NotSupportedError, match="not found"):
        mgr.submit("heatmap", {"objects_name": "nuclei", "feature": "Bogus"})


def test_tool_cli(store_with_features, capsys):
    """tmx tool submit/list/available (reference tm_tool CLI)."""
    import json

    from tmlibrary_tpu.cli import main

    root = str(store_with_features.root)
    assert main(["tool", "available"]) == 0
    out = capsys.readouterr().out
    assert "clustering" in out and "classification" in out

    assert main([
        "tool", "submit", "--root", root, "--name", "clustering",
        "--payload", '{"objects_name": "nuclei", "k": 2}',
    ]) == 0
    submitted = json.loads(capsys.readouterr().out)
    assert submitted["tool"] == "clustering"
    assert submitted["n_objects"] == 80

    assert main(["tool", "list", "--root", root]) == 0
    listed = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert len(listed) == 1 and listed[0]["tool"] == "clustering"


def test_device_trace_writes_profile(tmp_path):
    """device_trace produces a TensorBoard-compatible trace directory."""
    import jax.numpy as jnp

    from tmlibrary_tpu.profiling import device_trace

    with device_trace(tmp_path / "prof"):
        (jnp.arange(64.0) ** 2).sum().block_until_ready()
    files = list((tmp_path / "prof").rglob("*"))
    assert any(f.is_file() for f in files)


def test_device_trace_none_is_noop():
    from tmlibrary_tpu.profiling import device_trace

    with device_trace(None):
        pass


def test_request_lifecycle_sync(store_with_features):
    """Synchronous submit still records the full request lifecycle."""
    mgr = ToolRequestManager(store_with_features)
    mgr.submit("clustering", {"objects_name": "nuclei", "k": 2})
    reqs = mgr.list_requests()
    assert len(reqs) == 1
    req = reqs[0]
    assert req["state"] == "done"
    assert req["tool"] == "clustering"
    assert req["n_objects"] == 80
    assert req["finished_at"] >= req["started_at"] >= req["submitted_at"]
    # status() round-trips by id and keeps the payload
    full = mgr.status(req["request"])
    assert full["payload"] == {"objects_name": "nuclei", "k": 2}


def test_request_lifecycle_failed(store_with_features):
    mgr = ToolRequestManager(store_with_features)
    with pytest.raises(Exception):
        mgr.submit("heatmap", {"objects_name": "nuclei", "feature": "Bogus"})
    (req,) = mgr.list_requests()
    assert req["state"] == "failed"
    assert "Bogus" in req["error"]
    # unknown tool fails at submit, before any request dir exists
    with pytest.raises(RegistryError):
        mgr.create_request("nope", {})
    assert len(mgr.list_requests()) == 1


def test_request_background_end_to_end(store_with_features, monkeypatch):
    """--background spawns a detached job whose state transitions to done
    (reference ToolJob fan-out)."""
    import time

    # the child must not inherit a pinned-but-possibly-dead TPU relay
    monkeypatch.setenv("TMX_PLATFORM", "cpu")

    mgr = ToolRequestManager(store_with_features)
    request_id = mgr.submit_async("clustering", {"objects_name": "nuclei", "k": 2})
    assert mgr.status(request_id)["state"] in ("submitted", "running", "done")
    deadline = time.time() + 120
    while time.time() < deadline:
        state = mgr.status(request_id)["state"]
        if state in ("done", "failed"):
            break
        time.sleep(1)
    final = mgr.status(request_id)
    assert final["state"] == "done", final
    assert final["n_objects"] == 80
    # the detached job captured its log
    assert (store_with_features.tools_dir / request_id / "tool.log").exists()
    # and the result itself is loadable
    results = mgr.list_results()
    assert any(r["request"] == request_id for r in results)


def test_cli_tool_status_and_workflow_status(store_with_features, capsys):
    import json as _json

    from tmlibrary_tpu.cli import main

    root = str(store_with_features.root)
    assert main([
        "tool", "submit", "--root", root, "--name", "clustering",
        "--payload", '{"objects_name": "nuclei", "k": 2}',
    ]) == 0
    capsys.readouterr()
    assert main(["tool", "list", "--root", root]) == 0
    (line,) = capsys.readouterr().out.strip().splitlines()
    entry = _json.loads(line)
    assert entry["state"] == "done"
    assert main(["tool", "status", "--root", root,
                 "--request", entry["request"]]) == 0
    status = _json.loads(capsys.readouterr().out)
    assert status["state"] == "done" and "payload" in status


def test_same_millisecond_requests_get_distinct_ids(store_with_features,
                                                   monkeypatch):
    import time as _time

    mgr = ToolRequestManager(store_with_features)
    monkeypatch.setattr(_time, "time", lambda: 1234.567)
    a = mgr.create_request("clustering", {"k": 2})
    b = mgr.create_request("clustering", {"k": 3})
    assert a != b
    assert mgr.status(a)["payload"] == {"k": 2}
    assert mgr.status(b)["payload"] == {"k": 3}


def test_status_of_pre_ledger_result_dir(store_with_features):
    d = store_with_features.tools_dir / "clustering_legacy"
    d.mkdir(parents=True)
    (d / "result.json").write_text('{"tool": "clustering"}')
    mgr = ToolRequestManager(store_with_features)
    assert mgr.status("clustering_legacy") == {
        "request": "clustering_legacy", "state": "done"
    }


def test_tools_on_spatial_mosaic_features(tmp_path, devices):
    """Tools compose with the spatial layout's ragged per-well feature
    tables (site_index -1, global labels): heatmap + k-means clustering
    run unchanged on mosaic_cells features."""
    import numpy as np

    from tmlibrary_tpu.models.experiment import grid_experiment
    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.tools.base import ToolRequestManager
    from tmlibrary_tpu.workflow.registry import get_step

    exp = grid_experiment(
        "tools_sp", well_rows=1, well_cols=1, sites_per_well=(2, 2),
        channel_names=("DAPI",), site_shape=(32, 32),
    )
    st = ExperimentStore.create(tmp_path / "tools_sp_exp", exp)
    rng = np.random.default_rng(5)
    yy, xx = np.mgrid[0:64, 0:64]
    mosaic = rng.normal(300, 15, (64, 64))
    # two small dim nuclei + two large bright ones -> 2 k-means clusters
    for cy, cx, amp, s2 in [(16, 16, 5000, 4.0), (48, 16, 5000, 4.0),
                            (16, 48, 5000, 30.0), (48, 48, 5000, 30.0)]:
        mosaic += amp * np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * s2))
    mosaic = np.clip(mosaic, 0, 65535).astype(np.uint16)
    st.write_sites(np.stack([mosaic[:32, :32], mosaic[:32, 32:],
                             mosaic[32:, :32], mosaic[32:, 32:]]),
                   [0, 1, 2, 3], channel=0)
    jt = get_step("jterator")(st)
    jt.init({"layout": "spatial", "n_devices": 8})
    assert jt.run(0)["objects"]["mosaic_cells"] == 4

    mgr = ToolRequestManager(st)
    heat = mgr.submit("heatmap", {"objects_name": "mosaic_cells",
                                  "feature": "Morphology_area"})
    assert heat.layer_type == "continuous"
    assert len(heat.values) == 4
    assert (heat.values["site_index"] == -1).all()  # mosaic frame
    assert heat.attributes["max"] > heat.attributes["min"]

    clus = mgr.submit("clustering", {
        "objects_name": "mosaic_cells", "k": 2,
        "features": ["Morphology_area", "Intensity_mean_DAPI"],
    })
    labels_by_obj = dict(zip(clus.values["label"], clus.values["value"]))
    # the two big/bright objects cluster together, apart from the small
    feats = st.read_features("mosaic_cells").sort_values("label")
    order = np.argsort(feats["Morphology_area"].to_numpy())
    small = [int(feats.iloc[i]["label"]) for i in order[:2]]
    big = [int(feats.iloc[i]["label"]) for i in order[2:]]
    assert labels_by_obj[small[0]] == labels_by_obj[small[1]]
    assert labels_by_obj[big[0]] == labels_by_obj[big[1]]
    assert labels_by_obj[small[0]] != labels_by_obj[big[0]]


def test_classification_reports_training_metrics(store_with_features):
    """Training accuracy and per-class counts land in
    ToolResult.attributes (round-3 VERDICT next-step #8) so degenerate
    training sets are visible in the result."""
    mgr = ToolRequestManager(store_with_features)
    examples = [
        {"site_index": 0, "label": 1, "class": "dim"},
        {"site_index": 0, "label": 2, "class": "dim"},
        {"site_index": 0, "label": 11, "class": "bright"},
        {"site_index": 1, "label": 13, "class": "bright"},
        {"site_index": 2, "label": 14, "class": "bright"},
    ]
    result = mgr.submit(
        "classification",
        {"objects_name": "nuclei", "training_examples": examples},
    )
    attrs = result.attributes
    assert attrs["training_accuracy"] == 1.0  # well-separated populations
    assert attrs["class_counts"]["training"] == {"dim": 2, "bright": 3}
    pred = attrs["class_counts"]["predicted"]
    assert pred["dim"] + pred["bright"] == 80
    assert 35 <= pred["dim"] <= 45  # 40 true dims across 4 sites


def test_classification_select_k_best(store_with_features, rng):
    """select_k_best keeps the most class-separating features: with two
    informative columns and one pure-noise column, k=2 must drop the
    noise and still classify perfectly."""
    # add a noise feature column to the persisted table
    table = store_with_features.read_features("nuclei")
    table["Noise_feature"] = rng.normal(0, 1, len(table))
    store_with_features.append_features("nuclei", table, shard="batch_000")

    mgr = ToolRequestManager(store_with_features)
    examples = [
        {"site_index": 0, "label": l, "class": "dim"} for l in (1, 2, 3)
    ] + [
        {"site_index": 0, "label": l, "class": "bright"} for l in (11, 12, 13)
    ]
    result = mgr.submit(
        "classification",
        {"objects_name": "nuclei", "training_examples": examples,
         "select_k_best": 2},
    )
    kept = result.attributes["features"]
    assert len(kept) == 2 and "Noise_feature" not in kept
    assert result.attributes["training_accuracy"] == 1.0


def test_feature_matrix_sanitizes_nan(store_with_features):
    """A NaN feature value (degenerate-object solidity) must not poison
    the standardized matrix."""
    table = store_with_features.read_features("nuclei")
    table.loc[0, "Morphology_area"] = np.nan
    store_with_features.append_features("nuclei", table, shard="batch_000")
    tool = get_tool("classification")(store_with_features)
    ids, x, cols = tool.load_feature_matrix("nuclei")
    assert np.isfinite(x).all()
    # imputed with the column finite mean -> z of ~0, not an outlier
    assert abs(x[0, cols.index("Morphology_area")]) < 0.05


def test_label_layer_export_site_values(store_with_features):
    """Viewer-style per-site export: values image carries each object's
    mapped value on its pixels, background 0."""
    # persist tiny label images: site 0 has objects 1 and 11
    labels = np.zeros((1, 16, 16), np.int32)
    labels[0, 2:5, 2:5] = 1
    labels[0, 9:12, 9:12] = 11
    store_with_features.write_labels(labels, [0], "nuclei")

    mgr = ToolRequestManager(store_with_features)
    result = mgr.submit(
        "classification",
        {"objects_name": "nuclei", "training_examples": [
            {"site_index": 0, "label": 1, "class": "dim"},
            {"site_index": 0, "label": 11, "class": "bright"},
        ]},
    )
    layer = result.label_layer()
    out = layer.export_site_values(
        store_with_features, store_with_features.root / "layer_export"
    )
    by_site = {p.name: p for p in out}
    assert "site_00000.npz" in by_site
    data = np.load(by_site["site_00000.npz"])
    np.testing.assert_array_equal(data["labels"], labels[0])
    v = result.values
    want_1 = float(v[(v["site_index"] == 0) & (v["label"] == 1)]["value"].iloc[0])
    want_11 = float(v[(v["site_index"] == 0) & (v["label"] == 11)]["value"].iloc[0])
    assert data["values"][3, 3] == want_1
    assert data["values"][10, 10] == want_11
    # class id 0 is a real value, so background is NaN, not 0
    assert {want_1, want_11} == {0.0, 1.0}
    assert np.isnan(data["values"][0, 0])


def test_kbest_keeps_perfect_separator():
    """A feature constant within each class but different between them
    is a PERFECT separator (F = inf), never scored below noise."""
    from tmlibrary_tpu.tools.classification import _kbest_anova

    rng = np.random.default_rng(5)
    n = 20
    y = np.repeat(np.asarray([0, 1], np.int32), n // 2)
    perfect = y.astype(np.float64)  # zero within-class variance
    noise = rng.normal(0, 1, (n, 2))
    x = np.column_stack([noise[:, 0], perfect, noise[:, 1]])
    keep = _kbest_anova(x, y, 2, 1)
    assert list(keep) == [1]
    # a fully constant column still scores 0 (not selected over noise)
    x2 = np.column_stack([np.ones(n), perfect])
    assert list(_kbest_anova(x2, y, 2, 1)) == [1]


def test_heatmap_plate_plot_and_robust_window(store_with_features):
    mgr = ToolRequestManager(store_with_features)
    result = mgr.submit(
        "heatmap", {"objects_name": "nuclei", "feature": "Morphology_area"}
    )
    attrs = result.attributes
    assert attrs["n_objects"] == 80
    assert attrs["min"] <= attrs["p01"] < attrs["p99"] <= attrs["max"]
    (plot,) = result.plots
    assert plot.type == "plate_heatmap"
    wells = plot.figure["wells"]
    assert len(wells) == 1  # one well in the fixture
    table = store_with_features.read_features("nuclei")
    np.testing.assert_allclose(
        wells[0]["mean"], table["Morphology_area"].mean()
    )


def test_heatmap_emits_all_nan_well_with_null_mean(tmp_path, rng):
    """An all-NaN well (every object's feature degenerate) stays in the
    plate_heatmap wells list with ``mean: null`` — dropping it would be
    indistinguishable from a well outside the plate (round-4 advisor)."""
    exp = grid_experiment(name="nanwell", well_rows=1, well_cols=2,
                          sites_per_well=(1, 1), site_shape=(16, 16))
    store = ExperimentStore.create(tmp_path / "exp", exp)
    rows = []
    for well_col in (0, 1):
        for label in range(1, 4):
            rows.append({
                "site_index": well_col,
                "plate": "plate00",
                "well_row": 0,
                "well_col": well_col,
                "site_y": 0,
                "site_x": 0,
                "label": label,
                "Morphology_area": np.nan if well_col else 100.0 + label,
            })
    store.append_features("nuclei", pd.DataFrame(rows), shard="batch_000")
    result = ToolRequestManager(store).submit(
        "heatmap", {"objects_name": "nuclei", "feature": "Morphology_area"}
    )
    (plot,) = result.plots
    wells = {w["well_col"]: w["mean"] for w in plot.figure["wells"]}
    assert wells[1] is None
    np.testing.assert_allclose(wells[0], 102.0)
    # and the serialized payload is strict JSON (no literal NaN)
    import json

    json.loads(json.dumps(plot.figure))


def test_clustering_reports_sizes_and_inertia(store_with_features):
    mgr = ToolRequestManager(store_with_features)
    result = mgr.submit("clustering", {"objects_name": "nuclei", "k": 2})
    attrs = result.attributes
    sizes = attrs["cluster_sizes"]
    assert sorted(sizes.values()) == [40, 40]  # two equal populations
    assert attrs["inertia"] > 0
