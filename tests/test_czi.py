"""First-party Zeiss CZI (ZISRAW) container support — second entry in the
Bio-Formats-gap program after ND2.

``write_czi`` emits the layout ``CZIReader`` documents: 32-byte segment
headers, a ZISRAWFILE header segment pointing at a ZISRAWDIRECTORY of
DirectoryEntryDV records, and uncompressed Gray16 ZISRAWSUBBLOCK segments
whose data starts at payload offset max(256, 16+entry) + metadata."""
import struct

import numpy as np
import pytest

from tmlibrary_tpu.errors import MetadataError
from tmlibrary_tpu.readers import CZIReader


def _segment(sid: bytes, payload: bytes) -> bytes:
    header = sid.ljust(16, b"\x00") + struct.pack("<qq", len(payload), len(payload))
    return header + payload


def _entry(pixel_type, file_pos, compression, dims) -> bytes:
    """dims: list of (name, start, size)."""
    out = b"DV" + struct.pack("<iqii", pixel_type, file_pos, 0, compression)
    out += b"\x00" * 6  # PyramidType + reserved
    out += struct.pack("<i", len(dims))
    for name, start, size in dims:
        out += name.encode().ljust(4, b"\x00")
        out += struct.pack("<iifi", start, size, float(start), size)
    return out


def _compress(data: bytes, compression: int, hilo: bool = False) -> bytes:
    """Test-side encode for zstd0 (5) / zstd1 (6, with optional hi-lo
    byte packing) subblock payloads."""
    import zstandard

    if compression == 0:
        return data
    if hilo:
        a = np.frombuffer(data, "<u2")
        data = (a & 0xFF).astype(np.uint8).tobytes() + (a >> 8).astype(
            np.uint8).tobytes()
    frame = zstandard.ZstdCompressor().compress(data)
    if compression == 6:
        return bytes([3, 1, int(hilo)]) + frame
    return frame


def write_czi(path, planes: np.ndarray, pixel_type=1, compression=0,
              hilo=False) -> None:
    """``planes``: (S, C, H, W) uint16 — one z-plane, one tpoint."""
    n_s, n_c, h, w = planes.shape
    blob = bytearray()
    # file header segment: payload with directory position at offset 36
    file_payload = bytearray(512)
    blob.extend(_segment(b"ZISRAWFILE", bytes(file_payload)))

    entries = []
    for s in range(n_s):
        for c in range(n_c):
            dims = [("X", 0, w), ("Y", 0, h), ("C", c, 1), ("Z", 0, 1),
                    ("T", 0, 1), ("S", s, 1)]
            file_pos = len(blob)
            entry = _entry(pixel_type, file_pos, compression, dims)
            data = _compress(planes[s, c].tobytes(), compression, hilo)
            sub_payload = bytearray(struct.pack("<iiq", 0, 0, len(data)))
            sub_payload += entry
            pad = max(256, 16 + len(entry)) - len(sub_payload)
            sub_payload += b"\x00" * pad
            sub_payload += data
            blob.extend(_segment(b"ZISRAWSUBBLOCK", bytes(sub_payload)))
            entries.append(_entry(pixel_type, file_pos, compression, dims))

    dir_pos = len(blob)
    dir_payload = struct.pack("<i", len(entries)) + b"\x00" * 124
    dir_payload += b"".join(entries)
    blob.extend(_segment(b"ZISRAWDIRECTORY", dir_payload))
    # patch DirectoryPosition into the file header payload at the spec
    # offset: major(4) minor(4) reserved(8) guids(32) file_part(4) = 52
    struct.pack_into("<q", blob, 32 + 52, dir_pos)
    path.write_bytes(bytes(blob))


@pytest.fixture()
def planes():
    rng = np.random.default_rng(41)
    return rng.integers(0, 4000, (3, 2, 24, 40), dtype=np.uint16)


def test_czi_reader_round_trip(tmp_path, planes):
    path = tmp_path / "exp.czi"
    write_czi(path, planes)
    with CZIReader(path) as r:
        assert (r.width, r.height) == (40, 24)
        assert r.n_scenes == 3 and r.n_channels == 2
        assert r.n_zplanes == 1 and r.n_tpoints == 1
        for s in range(3):
            for c in range(2):
                np.testing.assert_array_equal(
                    r.read_plane(s, c), planes[s, c]
                )
                np.testing.assert_array_equal(
                    r.read_plane_linear(s * 2 + c), planes[s, c]
                )


def test_czi_reader_rejects_garbage(tmp_path):
    path = tmp_path / "junk.czi"
    path.write_bytes(b"definitely not zisraw" * 8)
    with pytest.raises(MetadataError, match="not a CZI"):
        CZIReader(path).__enter__()


def test_czi_reader_rejects_compressed(tmp_path, planes):
    path = tmp_path / "jxr.czi"
    write_czi(path, planes, compression=4)  # JPEG-XR
    with CZIReader(path) as r:
        with pytest.raises(MetadataError, match="compressed"):
            r.read_plane(0, 0)


def test_czi_reader_rejects_non_gray16(tmp_path, planes):
    path = tmp_path / "f32.czi"
    write_czi(path, planes, pixel_type=12)  # Gray32Float
    with CZIReader(path) as r:
        with pytest.raises(MetadataError, match="Gray16"):
            r.read_plane(0, 0)


def test_czi_truncated_raises_metadata_error(tmp_path, planes):
    path = tmp_path / "good.czi"
    write_czi(path, planes)
    blob = path.read_bytes()
    bad = tmp_path / "trunc.czi"
    bad.write_bytes(blob[: len(blob) // 2])
    with pytest.raises(MetadataError):
        CZIReader(bad).__enter__()


def test_czi_ingest_end_to_end(tmp_path, planes):
    """per-well .czi files -> metaconfig (auto) -> imextract -> store."""
    from tmlibrary_tpu.models.experiment import Experiment
    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.workflow.registry import get_step

    src = tmp_path / "source"
    src.mkdir()
    rng = np.random.default_rng(43)
    wells = {}
    for well in ("A01", "B02"):
        data = rng.integers(0, 4000, (3, 2, 24, 40), dtype=np.uint16)
        write_czi(src / f"scan_{well}.czi", data)
        wells[well] = data

    root = tmp_path / "exp"
    store = ExperimentStore.create(
        root,
        Experiment(name="czitest", plates=[], channels=[],
                   site_height=1, site_width=1),
    )
    meta = get_step("metaconfig")(store)
    meta.init({"source_dir": str(src), "handler": "auto"})
    result = meta.run(0)
    assert result["n_files"] == 2 * 3 * 2  # wells x scenes x channels

    exp = ExperimentStore.open(root).experiment
    assert exp.n_sites == 6
    assert {c.name for c in exp.channels} == {"C00", "C01"}

    ime = get_step("imextract")(store)
    ime.init({})
    for j in ime.list_batches():
        ime.run(j)

    store = ExperimentStore.open(root)
    for ch in range(2):
        pixels = store.read_sites(None, channel=ch)
        np.testing.assert_array_equal(pixels[:3], wells["A01"][:, ch])
        np.testing.assert_array_equal(pixels[3:], wells["B02"][:, ch])


def test_czi_nonzero_based_z_normalized(tmp_path):
    """Substack acquisitions carry non-0-based Z starts; they must map to
    dense zplane indices (review catch: raw starts made them unreadable)."""
    rng = np.random.default_rng(53)
    n_s, n_c, h, w = 1, 1, 16, 16
    vols = rng.integers(0, 4000, (3, h, w), dtype=np.uint16)
    blob = bytearray()
    file_payload = bytearray(512)
    blob.extend(_segment(b"ZISRAWFILE", bytes(file_payload)))
    entries = []
    for zi, zstart in enumerate((2, 3, 4)):
        dims = [("X", 0, w), ("Y", 0, h), ("C", 0, 1), ("Z", zstart, 1),
                ("T", 0, 1), ("S", 0, 1)]
        file_pos = len(blob)
        entry = _entry(1, file_pos, 0, dims)
        data = vols[zi].tobytes()
        sub = bytearray(struct.pack("<iiq", 0, 0, len(data)))
        sub += entry
        sub += b"\x00" * (max(256, 16 + len(entry)) - len(sub))
        sub += data
        blob.extend(_segment(b"ZISRAWSUBBLOCK", bytes(sub)))
        entries.append(entry)
    dir_pos = len(blob)
    dir_payload = struct.pack("<i", len(entries)) + b"\x00" * 124 + b"".join(entries)
    blob.extend(_segment(b"ZISRAWDIRECTORY", dir_payload))
    struct.pack_into("<q", blob, 32 + 52, dir_pos)
    path = tmp_path / "substack.czi"
    path.write_bytes(bytes(blob))

    with CZIReader(path) as r:
        assert r.n_zplanes == 3
        for zi in range(3):
            np.testing.assert_array_equal(r.read_plane(0, 0, zplane=zi), vols[zi])


@pytest.mark.parametrize("compression,hilo", [(5, False), (6, False), (6, True)])
def test_czi_zstd_subblocks_round_trip(tmp_path, planes, compression, hilo):
    """zstd0 and zstd1 (with and without hi-lo byte packing) decode
    bit-identically — the modern ZEN compression default."""
    path = tmp_path / "z.czi"
    write_czi(path, planes, compression=compression, hilo=hilo)
    with CZIReader(path) as r:
        for s in range(3):
            for c in range(2):
                np.testing.assert_array_equal(
                    r.read_plane(s, c), planes[s, c]
                )


def test_czi_corrupt_zstd_rejected(tmp_path, planes):
    path = tmp_path / "bad.czi"
    write_czi(path, planes, compression=5)
    blob = bytearray(path.read_bytes())
    # stomp on the first subblock's compressed bytes
    pos = blob.find(b"ZISRAWSUBBLOCK") + 300
    blob[pos:pos + 40] = b"\xff" * 40
    path.write_bytes(bytes(blob))
    with CZIReader(path) as r:
        with pytest.raises(MetadataError):
            r.read_plane(0, 0)


def test_czi_zstd_bomb_rejected_before_allocation(tmp_path):
    """A small frame declaring a huge decompressed size must be rejected
    up front — max_output_size does NOT cap frames with an embedded
    content size, so the naive path would allocate it in full."""
    import zstandard

    from tmlibrary_tpu.readers import _czi_zstd_plane

    bomb = zstandard.ZstdCompressor().compress(b"\x00" * 50_000_000)
    assert len(bomb) < 10_000  # it really is a bomb
    with pytest.raises(MetadataError, match="declares"):
        _czi_zstd_plane(bomb, 8, 8, False, "bomb.czi")
