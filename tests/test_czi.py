"""First-party Zeiss CZI (ZISRAW) container support — second entry in the
Bio-Formats-gap program after ND2.

``write_czi`` emits the layout ``CZIReader`` documents: 32-byte segment
headers, a ZISRAWFILE header segment pointing at a ZISRAWDIRECTORY of
DirectoryEntryDV records, and uncompressed Gray16 ZISRAWSUBBLOCK segments
whose data starts at payload offset max(256, 16+entry) + metadata."""
import struct

import numpy as np
import pytest

from tmlibrary_tpu.errors import MetadataError
from tmlibrary_tpu.readers import CZIReader


def _segment(sid: bytes, payload: bytes) -> bytes:
    header = sid.ljust(16, b"\x00") + struct.pack("<qq", len(payload), len(payload))
    return header + payload


def _entry(pixel_type, file_pos, compression, dims, pyramid=0) -> bytes:
    """dims: list of (name, start, size)."""
    out = b"DV" + struct.pack("<iqii", pixel_type, file_pos, 0, compression)
    out += bytes([pyramid]) + b"\x00" * 5  # PyramidType + reserved
    out += struct.pack("<i", len(dims))
    for name, start, size in dims:
        out += name.encode().ljust(4, b"\x00")
        out += struct.pack("<iifi", start, size, float(start), size)
    return out


def _compress(data: bytes, compression: int, hilo: bool = False,
              plane: "np.ndarray | None" = None) -> bytes:
    """Test-side encode for zstd0 (5) / zstd1 (6, with optional hi-lo
    byte packing) and JPEG (1, needs ``plane``) subblock payloads."""
    if compression == 0:
        return data
    if compression == 1:
        import cv2

        ok, buf = cv2.imencode(".jpg", plane)
        assert ok
        return buf.tobytes()
    # only the zstd encodings need the optional codec — uncompressed and
    # JPEG paths above must keep working in environments without it
    zstandard = pytest.importorskip(
        "zstandard", reason="zstd test encode needs the optional "
        "zstandard package (the reader degrades to MetadataError without "
        "it — covered by test_czi_zstd_without_module_errors)"
    )
    if hilo:
        a = np.frombuffer(data, "<u2")
        data = (a & 0xFF).astype(np.uint8).tobytes() + (a >> 8).astype(
            np.uint8).tobytes()
    frame = zstandard.ZstdCompressor().compress(data)
    if compression == 6:
        return bytes([3, 1, int(hilo)]) + frame
    return frame


def metadata_xml(channel_names) -> bytes:
    chans = "".join(
        f'<Channel Id="Channel:{i}" Name="{n}"/>'
        for i, n in enumerate(channel_names)
    )
    doc = ("<ImageMetadata><Metadata><Information><Image><Dimensions>"
           f"<Channels>{chans}</Channels>"
           "</Dimensions></Image></Information></Metadata></ImageMetadata>")
    return doc.encode()


def write_czi(path, planes: np.ndarray, pixel_type=1, compression=0,
              hilo=False, n_tiles=1, with_pyramid=False,
              global_m=False, tile_origins=None,
              channel_names=None) -> None:
    """``planes``: (S, C, H, W) uint16 — one z-plane, one tpoint.  With
    ``n_tiles`` > 1 the S axis is reinterpreted as S*M (mosaic tiles,
    S fastest-outer): planes[s*M+m] carries dims S=s, M=m.  With
    ``with_pyramid`` a half-size pyramid copy of each subblock is
    interleaved (must be skipped by the reader)."""
    n_sm, n_c, h, w = planes.shape
    assert n_sm % n_tiles == 0
    blob = bytearray()
    # file header segment: payload with directory position at offset 36
    file_payload = bytearray(512)
    blob.extend(_segment(b"ZISRAWFILE", bytes(file_payload)))

    def add_subblock(data, dims, pyramid=0):
        file_pos = len(blob)
        entry = _entry(pixel_type, file_pos, compression, dims, pyramid)
        sub_payload = bytearray(struct.pack("<iiq", 0, 0, len(data)))
        sub_payload += entry
        pad = max(256, 16 + len(entry)) - len(sub_payload)
        sub_payload += b"\x00" * pad
        sub_payload += data
        blob.extend(_segment(b"ZISRAWSUBBLOCK", bytes(sub_payload)))
        entries.append(_entry(pixel_type, file_pos, compression, dims, pyramid))

    entries = []
    for sm in range(n_sm):
        s, m = divmod(sm, n_tiles)
        for c in range(n_c):
            y0, x0 = (tile_origins[m] if tile_origins else (0, 0))
            dims = [("X", x0, w), ("Y", y0, h), ("C", c, 1), ("Z", 0, 1),
                    ("T", 0, 1), ("S", s, 1)]
            if n_tiles > 1:
                dims.append(("M", sm if global_m else m, 1))
            add_subblock(
                _compress(planes[sm, c].tobytes(), compression, hilo,
                          plane=planes[sm, c]), dims)
            if with_pyramid:
                half = planes[sm, c][::2, ::2]
                pdims = [("X", 0, half.shape[1]), ("Y", 0, half.shape[0]),
                         ("C", c, 1), ("Z", 0, 1), ("T", 0, 1), ("S", s, 1)]
                add_subblock(
                    _compress(half.tobytes(), compression, hilo,
                              plane=half), pdims,
                    pyramid=1)

    meta_pos = 0
    if channel_names is not None:
        meta_pos = len(blob)
        xml = metadata_xml(channel_names)
        meta_payload = struct.pack("<ii", len(xml), 0) + b"\x00" * 248 + xml
        blob.extend(_segment(b"ZISRAWMETADATA", meta_payload))
    dir_pos = len(blob)
    dir_payload = struct.pack("<i", len(entries)) + b"\x00" * 124
    dir_payload += b"".join(entries)
    blob.extend(_segment(b"ZISRAWDIRECTORY", dir_payload))
    # patch DirectoryPosition (and MetadataPosition, which follows it)
    # into the file header payload at the spec offset:
    # major(4) minor(4) reserved(8) guids(32) file_part(4) = 52
    struct.pack_into("<q", blob, 32 + 52, dir_pos)
    struct.pack_into("<q", blob, 32 + 60, meta_pos)
    path.write_bytes(bytes(blob))


@pytest.fixture()
def planes():
    rng = np.random.default_rng(41)
    return rng.integers(0, 4000, (3, 2, 24, 40), dtype=np.uint16)


def test_czi_reader_round_trip(tmp_path, planes):
    path = tmp_path / "exp.czi"
    write_czi(path, planes)
    with CZIReader(path) as r:
        assert (r.width, r.height) == (40, 24)
        assert r.n_scenes == 3 and r.n_channels == 2
        assert r.n_zplanes == 1 and r.n_tpoints == 1
        for s in range(3):
            for c in range(2):
                np.testing.assert_array_equal(
                    r.read_plane(s, c), planes[s, c]
                )
                np.testing.assert_array_equal(
                    r.read_plane_linear(s * 2 + c), planes[s, c]
                )


def test_czi_reader_rejects_garbage(tmp_path):
    path = tmp_path / "junk.czi"
    path.write_bytes(b"definitely not zisraw" * 8)
    with pytest.raises(MetadataError, match="not a CZI"):
        CZIReader(path).__enter__()


def test_czi_reader_rejects_compressed(tmp_path, planes):
    path = tmp_path / "jxr.czi"
    write_czi(path, planes, compression=4)  # JPEG-XR
    with CZIReader(path) as r:
        with pytest.raises(MetadataError, match="compressed"):
            r.read_plane(0, 0)


def test_czi_reader_rejects_non_gray16(tmp_path, planes):
    path = tmp_path / "f32.czi"
    write_czi(path, planes, pixel_type=12)  # Gray32Float
    with CZIReader(path) as r:
        with pytest.raises(MetadataError, match="Gray16"):
            r.read_plane(0, 0)


def test_czi_truncated_raises_metadata_error(tmp_path, planes):
    path = tmp_path / "good.czi"
    write_czi(path, planes)
    blob = path.read_bytes()
    bad = tmp_path / "trunc.czi"
    bad.write_bytes(blob[: len(blob) // 2])
    with pytest.raises(MetadataError):
        CZIReader(bad).__enter__()


def test_czi_ingest_end_to_end(tmp_path, planes):
    """per-well .czi files -> metaconfig (auto) -> imextract -> store."""
    from tmlibrary_tpu.models.experiment import Experiment
    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.workflow.registry import get_step

    src = tmp_path / "source"
    src.mkdir()
    rng = np.random.default_rng(43)
    wells = {}
    for well in ("A01", "B02"):
        data = rng.integers(0, 4000, (3, 2, 24, 40), dtype=np.uint16)
        write_czi(src / f"scan_{well}.czi", data)
        wells[well] = data

    root = tmp_path / "exp"
    store = ExperimentStore.create(
        root,
        Experiment(name="czitest", plates=[], channels=[],
                   site_height=1, site_width=1),
    )
    meta = get_step("metaconfig")(store)
    meta.init({"source_dir": str(src), "handler": "auto"})
    result = meta.run(0)
    assert result["n_files"] == 2 * 3 * 2  # wells x scenes x channels

    exp = ExperimentStore.open(root).experiment
    assert exp.n_sites == 6
    assert {c.name for c in exp.channels} == {"C00", "C01"}

    ime = get_step("imextract")(store)
    ime.init({})
    for j in ime.list_batches():
        ime.run(j)

    store = ExperimentStore.open(root)
    for ch in range(2):
        pixels = store.read_sites(None, channel=ch)
        np.testing.assert_array_equal(pixels[:3], wells["A01"][:, ch])
        np.testing.assert_array_equal(pixels[3:], wells["B02"][:, ch])


def test_czi_nonzero_based_z_normalized(tmp_path):
    """Substack acquisitions carry non-0-based Z starts; they must map to
    dense zplane indices (review catch: raw starts made them unreadable)."""
    rng = np.random.default_rng(53)
    n_s, n_c, h, w = 1, 1, 16, 16
    vols = rng.integers(0, 4000, (3, h, w), dtype=np.uint16)
    blob = bytearray()
    file_payload = bytearray(512)
    blob.extend(_segment(b"ZISRAWFILE", bytes(file_payload)))
    entries = []
    for zi, zstart in enumerate((2, 3, 4)):
        dims = [("X", 0, w), ("Y", 0, h), ("C", 0, 1), ("Z", zstart, 1),
                ("T", 0, 1), ("S", 0, 1)]
        file_pos = len(blob)
        entry = _entry(1, file_pos, 0, dims)
        data = vols[zi].tobytes()
        sub = bytearray(struct.pack("<iiq", 0, 0, len(data)))
        sub += entry
        sub += b"\x00" * (max(256, 16 + len(entry)) - len(sub))
        sub += data
        blob.extend(_segment(b"ZISRAWSUBBLOCK", bytes(sub)))
        entries.append(entry)
    dir_pos = len(blob)
    dir_payload = struct.pack("<i", len(entries)) + b"\x00" * 124 + b"".join(entries)
    blob.extend(_segment(b"ZISRAWDIRECTORY", dir_payload))
    struct.pack_into("<q", blob, 32 + 52, dir_pos)
    path = tmp_path / "substack.czi"
    path.write_bytes(bytes(blob))

    with CZIReader(path) as r:
        assert r.n_zplanes == 3
        for zi in range(3):
            np.testing.assert_array_equal(r.read_plane(0, 0, zplane=zi), vols[zi])


@pytest.mark.parametrize("compression,hilo", [(5, False), (6, False), (6, True)])
def test_czi_zstd_subblocks_round_trip(tmp_path, planes, compression, hilo):
    """zstd0 and zstd1 (with and without hi-lo byte packing) decode
    bit-identically — the modern ZEN compression default."""
    path = tmp_path / "z.czi"
    write_czi(path, planes, compression=compression, hilo=hilo)
    with CZIReader(path) as r:
        for s in range(3):
            for c in range(2):
                np.testing.assert_array_equal(
                    r.read_plane(s, c), planes[s, c]
                )


def test_czi_corrupt_zstd_rejected(tmp_path, planes):
    path = tmp_path / "bad.czi"
    write_czi(path, planes, compression=5)
    blob = bytearray(path.read_bytes())
    # stomp on the first subblock's compressed bytes
    pos = blob.find(b"ZISRAWSUBBLOCK") + 300
    blob[pos:pos + 40] = b"\xff" * 40
    path.write_bytes(bytes(blob))
    with CZIReader(path) as r:
        with pytest.raises(MetadataError):
            r.read_plane(0, 0)


def test_czi_zstd_bomb_rejected_before_allocation(tmp_path):
    """A small frame declaring a huge decompressed size must be rejected
    up front — max_output_size does NOT cap frames with an embedded
    content size, so the naive path would allocate it in full."""
    zstandard = pytest.importorskip("zstandard")

    from tmlibrary_tpu.readers import _czi_zstd_plane

    bomb = zstandard.ZstdCompressor().compress(b"\x00" * 50_000_000)
    assert len(bomb) < 10_000  # it really is a bomb
    with pytest.raises(MetadataError, match="declares"):
        _czi_zstd_plane(bomb, 8, 8, False, "bomb.czi")


def test_czi_mosaic_tiles_map_to_planes(tmp_path):
    """M-dimension mosaic tiles (slide scans) read per tile and through
    the (((s*M+m)*C+c)*Z+z)*T+t linear convention."""
    rng = np.random.default_rng(47)
    planes = rng.integers(0, 4000, (4, 2, 10, 12), dtype=np.uint16)
    path = tmp_path / "mosaic.czi"
    write_czi(path, planes, n_tiles=2)  # 2 scenes x 2 tiles
    with CZIReader(path) as r:
        assert (r.n_scenes, r.n_tiles, r.n_channels) == (2, 2, 2)
        for s in range(2):
            for m in range(2):
                for c in range(2):
                    np.testing.assert_array_equal(
                        r.read_plane(s, c, tile=m), planes[s * 2 + m, c]
                    )
                    np.testing.assert_array_equal(
                        r.read_plane_linear((s * 2 + m) * 2 + c),
                        planes[s * 2 + m, c],
                    )


def test_czi_pyramid_subblocks_skipped(tmp_path):
    rng = np.random.default_rng(48)
    planes = rng.integers(0, 4000, (2, 1, 10, 12), dtype=np.uint16)
    path = tmp_path / "pyr.czi"
    write_czi(path, planes, with_pyramid=True)
    with CZIReader(path) as r:
        assert (r.n_scenes, r.n_tiles, r.n_channels) == (2, 1, 1)
        assert (r.height, r.width) == (10, 12)  # not the half-size copy
        for s in range(2):
            np.testing.assert_array_equal(r.read_plane(s, 0), planes[s, 0])


def test_czi_mosaic_ingest_end_to_end(tmp_path):
    """Mosaic tiles become sites in the canonical store."""
    from tmlibrary_tpu.models.experiment import Experiment
    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.workflow.registry import get_step

    rng = np.random.default_rng(49)
    planes = rng.integers(0, 60000, (3, 1, 16, 20), dtype=np.uint16)
    src = tmp_path / "source"
    src.mkdir()
    write_czi(src / "slide_A01.czi", planes, n_tiles=3)  # 1 scene x 3 tiles

    root = tmp_path / "exp"
    store = ExperimentStore.create(
        root, Experiment(name="mosaic", plates=[], channels=[],
                         site_height=1, site_width=1))
    meta = get_step("metaconfig")(store)
    meta.init({"source_dir": str(src), "handler": "auto"})
    meta.run(0)
    exp = ExperimentStore.open(root).experiment
    assert exp.n_sites == 3

    ime = get_step("imextract")(store)
    ime.init({})
    for j in ime.list_batches():
        ime.run(j)
    st = ExperimentStore.open(root)
    px = st.read_sites(None, channel=0)
    for m in range(3):
        np.testing.assert_array_equal(px[m], planes[m, 0])


def test_czi_global_tile_numbering_ranks_per_scene(tmp_path):
    """ZEN commonly numbers M globally across scenes (scene 0: 0..1,
    scene 1: 2..3); tiles must rank per scene, not globally."""
    rng = np.random.default_rng(53)
    planes = rng.integers(0, 4000, (4, 1, 10, 12), dtype=np.uint16)
    path = tmp_path / "global_m.czi"
    write_czi(path, planes, n_tiles=2, global_m=True)
    with CZIReader(path) as r:
        assert (r.n_scenes, r.n_tiles) == (2, 2)
        for s in range(2):
            for m in range(2):
                np.testing.assert_array_equal(
                    r.read_plane(s, 0, tile=m), planes[s * 2 + m, 0]
                )


def test_czi_sparse_grid_rejected_at_open(tmp_path):
    """A missing (scene, tile) subblock must fail the OPEN (handler
    skips with a logged reason), not crash mid-extract."""
    rng = np.random.default_rng(54)
    planes = rng.integers(0, 4000, (4, 1, 10, 12), dtype=np.uint16)
    path = tmp_path / "sparse.czi"
    write_czi(path, planes, n_tiles=2)
    blob = bytearray(path.read_bytes())
    # chop the LAST directory entry by rewriting the count
    dirpos = blob.rfind(b"ZISRAWDIRECTORY")
    payload = dirpos + 32
    (count,) = struct.unpack_from("<i", blob, payload)
    struct.pack_into("<i", blob, payload, count - 1)
    path.write_bytes(bytes(blob))
    with pytest.raises(MetadataError, match="sparse"):
        CZIReader(path).__enter__()


def test_czi_mosaic_tile_origins_drive_the_well_grid(tmp_path):
    """Single-scene mosaics with dense pixel origins ingest in
    acquisition geometry: site = grid(y, x) from the subblock origins,
    not the raw M order."""
    from tmlibrary_tpu.models.experiment import Experiment
    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.workflow.registry import get_step

    rng = np.random.default_rng(57)
    planes = rng.integers(0, 60000, (4, 1, 10, 12), dtype=np.uint16)
    src = tmp_path / "source"
    src.mkdir()
    # acquisition order serpentine: M0=(0,0), M1=(0,12), M2=(10,12), M3=(10,0)
    origins = [(0, 0), (0, 12), (10, 12), (10, 0)]
    write_czi(src / "slide_A01.czi", planes, n_tiles=4,
              tile_origins=origins)

    root = tmp_path / "exp"
    store = ExperimentStore.create(
        root, Experiment(name="geo", plates=[], channels=[],
                         site_height=1, site_width=1))
    meta = get_step("metaconfig")(store)
    meta.init({"source_dir": str(src), "handler": "czi"})
    meta.run(0)
    exp = ExperimentStore.open(root).experiment
    assert exp.n_sites == 4

    ime = get_step("imextract")(store)
    ime.init({})
    for j in ime.list_batches():
        ime.run(j)
    px = ExperimentStore.open(root).read_sites(None, channel=0)
    # row-major grid linearisation: site 0=(0,0)=M0, 1=(0,1)=M1,
    # 2=(1,0)=M3, 3=(1,1)=M2
    np.testing.assert_array_equal(px[0], planes[0, 0])
    np.testing.assert_array_equal(px[1], planes[1, 0])
    np.testing.assert_array_equal(px[2], planes[3, 0])
    np.testing.assert_array_equal(px[3], planes[2, 0])


def test_czi_sparse_origins_fall_back_to_m_order(tmp_path):
    """Origins that do not form a dense rectangle (L-shaped scan) keep
    the raw M-order site mapping."""
    from tmlibrary_tpu.workflow.steps.vendors import czi_sidecar

    rng = np.random.default_rng(58)
    planes = rng.integers(0, 60000, (3, 1, 10, 12), dtype=np.uint16)
    src = tmp_path / "source"
    src.mkdir()
    write_czi(src / "L_A01.czi", planes, n_tiles=3,
              tile_origins=[(0, 0), (0, 12), (10, 0)])
    entries, skipped = czi_sidecar(src)
    assert skipped == 0
    assert all("site_y" not in e for e in entries)
    assert [e["site"] for e in entries] == [0, 1, 2]


def test_czi_channel_names_from_metadata(tmp_path, planes):
    """ZISRAWMETADATA channel names label the ingest channels (sanitized
    to the pattern charset); files without metadata keep C00..."""
    path = tmp_path / "named.czi"
    write_czi(path, planes, channel_names=("DAPI", "Alexa 488"))
    with CZIReader(path) as r:
        assert r.channel_names == ["DAPI", "Alexa 488"]

    from tmlibrary_tpu.workflow.steps.vendors import czi_sidecar

    src = tmp_path / "source"
    src.mkdir()
    write_czi(src / "w_A01.czi", planes, channel_names=("DAPI", "Alexa 488"))
    entries, _ = czi_sidecar(src)
    assert {e["channel"] for e in entries} == {"DAPI", "Alexa-488"}

    bare = tmp_path / "bare.czi"
    write_czi(bare, planes)
    with CZIReader(bare) as r:
        assert r.channel_names is None


def test_czi_channel_names_guarded(tmp_path, planes):
    """Name-count mismatch (substack export keeps the full XML list) and
    decoy Channels blocks must not mislabel channels; an XML encoding
    declaration must not drop valid names."""
    # 3 names for 2 subblock channels -> degrade to C00...
    path = tmp_path / "mismatch.czi"
    write_czi(path, planes, channel_names=("A", "B", "C"))
    with CZIReader(path) as r:
        assert r.channel_names is None

    # decoy DisplaySetting/Channels BEFORE the Information path + an
    # encoding declaration: the explicit path must still win
    doc = (
        '<?xml version="1.0" encoding="utf-8"?>'
        "<ImageMetadata><Metadata>"
        "<DisplaySetting><Channels>"
        '<Channel Name="WRONG1"/><Channel Name="WRONG2"/>'
        "</Channels></DisplaySetting>"
        "<Information><Image><Dimensions><Channels>"
        '<Channel Id="Channel:0" Name="DAPI"/>'
        '<Channel Id="Channel:1" Name="GFP"/>'
        "</Channels></Dimensions></Image></Information>"
        "</Metadata></ImageMetadata>"
    ).encode()
    payload = struct.pack("<ii", len(doc), 0) + b"\x00" * 248 + doc
    r = CZIReader.__new__(CZIReader)
    r.filename = tmp_path / "x.czi"
    r._segment_payload = lambda off, expect: memoryview(payload)
    assert r._channel_names_from_xml(1) == ["DAPI", "GFP"]


def test_czi_gray8_round_trip(tmp_path):
    """pixel_type 0 (Gray8) decodes uncompressed and zstd0."""
    rng = np.random.default_rng(81)
    planes8 = rng.integers(0, 255, (2, 1, 16, 20), dtype=np.uint8)
    for comp in (0, 5):
        path = tmp_path / f"g8_{comp}.czi"
        write_czi(path, planes8, pixel_type=0, compression=comp)
        with CZIReader(path) as r:
            for s in range(2):
                out = r.read_plane(s, 0, 0, 0, 0)
                assert out.dtype == np.uint8
                np.testing.assert_array_equal(out, planes8[s, 0])


def test_czi_jpeg_subblocks_decode_via_cv2(tmp_path):
    """compression=1 (legacy lossy JPEG) decodes; pixels equal cv2's own
    decode of the embedded stream (JPEG is lossy, so the original plane
    is only the approximate golden)."""
    import cv2

    rng = np.random.default_rng(82)
    planes8 = rng.integers(0, 255, (1, 1, 24, 24), dtype=np.uint8)
    path = tmp_path / "j.czi"
    write_czi(path, planes8, pixel_type=0, compression=1)
    ok, stream = cv2.imencode(".jpg", planes8[0, 0])
    golden = cv2.imdecode(stream, cv2.IMREAD_UNCHANGED)
    with CZIReader(path) as r:
        out = r.read_plane(0, 0, 0, 0, 0)
    np.testing.assert_array_equal(out, golden)
    # lossy but close to the source
    assert np.abs(out.astype(int) - planes8[0, 0].astype(int)).mean() < 12


def test_czi_zstd1_hilo_on_gray8_is_rejected(tmp_path):
    """hi-lo packing is 16-bit-specific; an 8-bit subblock claiming it
    must fail loudly, not deinterleave garbage."""
    from tmlibrary_tpu.errors import MetadataError

    rng = np.random.default_rng(83)
    planes8 = rng.integers(0, 255, (1, 1, 8, 10), dtype=np.uint8)
    path = tmp_path / "h8.czi"
    # write with compression=6/hilo=False, then flip the zstd1 header's
    # hilo byte in place (payload bytes are identical)
    write_czi(path, planes8, pixel_type=0, compression=6, hilo=False)
    blob = bytearray(path.read_bytes())
    marker = blob.find(b"\x03\x01\x00")
    assert marker > 0
    blob[marker + 2] = 1
    path.write_bytes(bytes(blob))
    with CZIReader(path) as r:
        with pytest.raises(MetadataError):
            r.read_plane(0, 0, 0, 0, 0)
