"""``tmx serve`` admission + daemon chaos suite (DESIGN.md §20).

Proves the serving tentpole guarantees: overload degrades to pinned,
deterministic rejection (never a crash), tenants are isolated (quotas,
retry budgets, scoped breakers, WDRR fairness), and any interruption —
SIGTERM drain, deadline expiry, injected admission faults — converges
to the same results as clean sequential runs.  The in-process tests use
a registered dummy step so the daemon loop stays fast; the real-pipeline
coalescing proof at the bottom exercises the full jterator stack, and
the real-process crossing lives in ``scripts/ci_serve_smoke.py``.
"""

import json
import os
import time

import pytest

from test_workflow import synth_site_image  # noqa: F401 — reused below

from tmlibrary_tpu import faults, resilience, serve, telemetry
from tmlibrary_tpu.models.experiment import Experiment
from tmlibrary_tpu.models.store import ExperimentStore
from tmlibrary_tpu.resilience import EXIT_PREEMPTED
from tmlibrary_tpu.workflow.admission import (
    RETRY_AFTER_S,
    SHED_REASONS,
    AdmissionConfig,
    AdmissionQueue,
    JobSpec,
)
from tmlibrary_tpu.workflow.api import Step
from tmlibrary_tpu.workflow.engine import (
    RunLedger,
    Workflow,
    WorkflowDescription,
    WorkflowStageDescription,
    WorkflowStepDescription,
)
from tmlibrary_tpu.workflow.registry import register_step


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    resilience.clear_preemption()
    telemetry.reset_registry(enabled=True)
    ServeDummy.SLEEP = 0.0
    yield
    faults.clear()
    resilience.clear_preemption()
    telemetry.reset_registry()
    ServeDummy.SLEEP = 0.0


# --------------------------------------------------------------- dummy step
@register_step("servedummy")
class ServeDummy(Step):
    """Four trivial batches with idempotent marker outputs — a replayed
    batch after drain/resume must leave identical bytes."""

    N_BATCHES = 4
    #: per-batch stall so a deadline deterministically lands mid-run
    SLEEP = 0.0

    def create_batches(self, args):
        return [{} for _ in range(self.N_BATCHES)]

    def run_batch(self, batch):
        if ServeDummy.SLEEP:
            time.sleep(ServeDummy.SLEEP)
        out = self.step_dir / f"out_{batch['index']:03d}.txt"
        out.write_text(f"payload-{batch['index']}")
        return {"i": batch["index"]}


def dummy_description():
    return WorkflowDescription(
        stages=[WorkflowStageDescription(
            name="test", steps=[WorkflowStepDescription(name="servedummy")]
        )]
    )


def make_exp(tmp_path, name):
    placeholder = Experiment(
        name=name, plates=[], channels=[], site_height=1, site_width=1
    )
    store = ExperimentStore.create(tmp_path / name, placeholder)
    dummy_description().save(store.workflow_dir / "workflow.yaml")
    return store


def spec(job_id, root, tenant="a", **kw):
    kw.setdefault("submitted_at", 1000.0)
    return JobSpec(job_id=job_id, root=str(root), tenant=tenant, **kw)


def dummy_outputs(store):
    step_dir = store.workflow_dir / "servedummy"
    return {p.name: p.read_text() for p in step_dir.glob("out_*.txt")}


# =========================================================== admission unit
def test_queue_full_shed_hysteresis_and_determinism():
    """At max_queue the queue sheds with the pinned queue_full
    retry-after and KEEPS shedding until drained below the low
    watermark; the whole decision sequence replays identically."""

    def run_sequence():
        q = AdmissionQueue(AdmissionConfig(
            max_queue=4, low_watermark=2, tenant_quota=99))
        decisions = []
        for i in range(6):
            decisions.append(q.offer(spec(f"j{i}", "/x", submitted_at=i)))
        # drain to 3: still above the low watermark -> still shedding
        q.take()
        decisions.append(q.offer(spec("late1", "/x", submitted_at=9)))
        # drain to 2 == low watermark -> hysteresis clears on next offer
        q.take()
        decisions.append(q.offer(spec("late2", "/x", submitted_at=10)))
        return [(d.admitted, d.reason, d.retry_after_s) for d in decisions]

    first = run_sequence()
    assert first[:4] == [(True, None, 0.0)] * 4
    assert first[4] == (False, "queue_full", RETRY_AFTER_S["queue_full"])
    assert first[5] == (False, "queue_full", 30.0)
    assert first[6] == (False, "queue_full", 30.0)  # hysteresis holds
    assert first[7] == (True, None, 0.0)  # drained to low watermark
    assert run_sequence() == first  # bit-for-bit deterministic


def test_tenant_quota_and_breaker_isolation():
    """One tenant's flood (or failure streak) never affects another."""
    q = AdmissionQueue(AdmissionConfig(
        max_queue=99, tenant_quota=2, breaker_threshold=2))
    assert q.offer(spec("a1", "/x", tenant="a")).admitted
    assert q.offer(spec("a2", "/x", tenant="a")).admitted
    d = q.offer(spec("a3", "/x", tenant="a"))
    assert (d.reason, d.retry_after_s) == ("tenant_quota", 15.0)
    assert q.offer(spec("b1", "/x", tenant="b")).admitted

    # two failures trip a's breaker; b keeps admitting
    q.record_result("a", ok=False)
    q.record_result("a", ok=False)
    q.take(), q.take(), q.take()  # empty the queue
    d = q.offer(spec("a4", "/x", tenant="a"))
    assert (d.reason, d.retry_after_s) == ("tenant_breaker_open", 60.0)
    assert q.offer(spec("b2", "/x", tenant="b")).admitted
    snap = q.snapshot(now=2000.0)
    assert snap["tenants"]["a"]["breaker"] == "open"
    assert snap["tenants"]["b"]["breaker"] == "closed"


def test_retry_budget_spend_and_refund():
    q = AdmissionQueue(AdmissionConfig(
        max_queue=99, tenant_quota=99, retry_budget=1))
    # first-attempt jobs never spend the budget
    assert q.offer(spec("f1", "/x")).admitted
    # a resubmission spends the single token ...
    assert q.offer(spec("r1", "/x", attempt=1)).admitted
    d = q.offer(spec("r2", "/x", attempt=2))
    assert (d.reason, d.retry_after_s) == ("retry_budget", 120.0)
    # ... and a success refunds it
    q.record_result("a", ok=True)
    assert q.offer(spec("r3", "/x", attempt=1)).admitted


def test_deadline_and_duplicate_rejected():
    q = AdmissionQueue(AdmissionConfig(), clock=lambda: 100.0)
    d = q.offer(spec("dead", "/x", deadline=99.0))
    assert (d.admitted, d.reason, d.retry_after_s) == (
        False, "deadline_expired", 0.0)
    assert q.offer(spec("j1", "/x")).admitted
    d = q.offer(spec("j1", "/x"))
    assert (d.reason, d.retry_after_s) == ("duplicate", 0.0)


def test_wdrr_weights_grant_proportional_service():
    """Weight 2 means two jobs per rotation; weight 0.5 means one every
    other rotation — and the schedule replays identically."""

    def order(weights):
        q = AdmissionQueue(AdmissionConfig(
            max_queue=99, tenant_quota=99, tenant_weights=weights))
        for i in range(4):
            q.offer(spec(f"x{i}", "/t", tenant="a", submitted_at=float(i)))
            q.offer(spec(f"y{i}", "/t", tenant="b", submitted_at=float(i)))
        out = []
        while (j := q.take()) is not None:
            out.append(j.job_id)
        return out

    assert order({"b": 2.0}) == [
        "x0", "y0", "y1", "x1", "y2", "y3", "x2", "x3"]
    assert order({"b": 0.5}) == [
        "x0", "x1", "y0", "x2", "x3", "y1", "y2", "y3"]
    assert order({"b": 2.0}) == order({"b": 2.0})


def test_within_tenant_priority_order_and_drain():
    q = AdmissionQueue(AdmissionConfig(max_queue=99, tenant_quota=99))
    q.offer(spec("lo", "/x", priority=0, submitted_at=1.0))
    q.offer(spec("hi", "/x", priority=5, submitted_at=2.0, attempt=3))
    q.offer(spec("mid", "/x", tenant="b", submitted_at=0.5))
    assert q.take().job_id == "hi"
    drained = q.drain()
    # deterministic (tenant, priority) order, attempt counts preserved
    assert [j.job_id for j in drained] == ["lo", "mid"]
    assert q.depth() == 0


# ============================================ ledger-derived serve metrics
def test_registry_from_ledger_serve_events():
    """A multi-tenant serve ledger reconstructs the same tmx_serve_*
    series the live daemon emits — with per-tenant labels, shed
    accounting, and duplicate-record drops (same host ledger read
    twice must not double-count)."""
    events = [
        {"host": "h0", "ts": 1.0, "event": "serve_started", "recovered": 0},
        {"host": "h0", "ts": 2.0, "event": "job_admitted", "job": "a-1",
         "tenant": "a"},
        # same ts, different job: must NOT collapse in dedup
        {"host": "h0", "ts": 2.0, "event": "job_admitted", "job": "a-2",
         "tenant": "a"},
        {"host": "h0", "ts": 3.0, "event": "job_rejected", "job": "b-1",
         "tenant": "b", "reason": "queue_full", "retry_after_s": 30.0},
        {"host": "h0", "ts": 4.0, "event": "job_rejected", "job": "b-2",
         "tenant": "b", "reason": "invalid_spec", "retry_after_s": 0.0},
        {"host": "h0", "ts": 5.0, "event": "job_done", "job": "a-1",
         "tenant": "a", "elapsed_s": 2.5},
        {"host": "h0", "ts": 6.0, "event": "job_failed", "job": "a-2",
         "tenant": "a", "error": "boom"},
        {"host": "h0", "ts": 7.0, "event": "job_expired", "job": "b-3",
         "tenant": "b"},
        {"host": "h0", "ts": 8.0, "event": "job_requeued", "job": "a-3",
         "tenant": "a", "phase": "drain"},
        {"host": "h0", "ts": 9.0, "event": "serve_preempted",
         "reason": "SIGTERM", "requeued": 2},
    ]
    reg = telemetry.registry_from_ledger(events + events)  # dup read
    c = lambda name, **lb: reg.counter(name, **lb).value  # noqa: E731
    assert c("tmx_serve_admitted_total", tenant="a", host="h0") == 2
    assert c("tmx_serve_rejected_total", tenant="b", reason="queue_full",
             host="h0") == 1
    # only overload reasons count as shed
    assert "queue_full" in SHED_REASONS and "invalid_spec" not in SHED_REASONS
    assert c("tmx_serve_shed_total", tenant="b", host="h0") == 1
    assert c("tmx_serve_jobs_done_total", tenant="a", host="h0") == 1
    assert c("tmx_serve_jobs_failed_total", tenant="a", host="h0") == 1
    assert c("tmx_serve_deadline_expired_total", tenant="b", host="h0") == 1
    assert c("tmx_serve_requeued_total", tenant="a", host="h0") == 1
    assert c("tmx_serve_preemptions_total", host="h0") == 1
    h = reg.histogram("tmx_serve_job_seconds", tenant="a", host="h0")
    assert h.count == 1 and h.sum == pytest.approx(2.5)


# ====================================================== daemon end to end
def test_serve_two_tenants_end_to_end(tmp_path, capsys):
    """Two tenants' jobs flow incoming -> admitted -> done, the serve
    ledger narrates each transition, and the status surfaces (CLI +
    serve_status_view) agree with the spool."""
    from tmlibrary_tpu.cli import main

    sroot = tmp_path / "srv"
    exp_a = make_exp(tmp_path, "expa")
    exp_b = make_exp(tmp_path, "expb")
    serve.enqueue_job(sroot, spec("a-1", exp_a.root, tenant="a"))
    # the second submission goes through the real CLI
    assert main(["enqueue", "--root", str(sroot),
                 "--experiment", str(exp_b.root),
                 "--tenant", "b", "--job-id", "b-1"]) == 0
    assert "enqueued b-1" in capsys.readouterr().out

    rc = serve.run_serve(sroot, poll_s=0.01, max_jobs=2,
                         install_handlers=False)
    assert rc == 0

    done = sorted(p.stem for p in serve.spool_dir(sroot, "done")
                  .glob("*.json"))
    assert done == ["a-1", "b-1"]
    assert not list(serve.spool_dir(sroot, "incoming").glob("*.json"))
    assert not list(serve.spool_dir(sroot, "admitted").glob("*.json"))
    assert dummy_outputs(exp_a) == {
        f"out_{i:03d}.txt": f"payload-{i}" for i in range(4)}

    events = RunLedger(serve.ledger_path(sroot)).events()
    kinds = [e["event"] for e in events]
    assert kinds[0] == "serve_started"
    for job, tenant in (("a-1", "a"), ("b-1", "b")):
        for kind in ("job_admitted", "job_started", "job_done"):
            assert any(e.get("event") == kind and e.get("job") == job
                       and e.get("tenant") == tenant for e in events)
    assert not any(e.get("event") == "step_failed" for e in events)

    view = serve.serve_status_view(sroot)
    assert view["spool"]["done"] == 2
    assert view["tenants"]["a"]["done"] == 1
    assert view["tenants"]["b"]["admitted"] == 1
    assert main(["serve", "status", "--root", str(sroot)]) == 0
    out = capsys.readouterr().out
    assert "serve root" in out and "a" in out and "b" in out
    assert main(["serve", "status", "--root", str(sroot), "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["spool"]["done"] == 2


def test_serve_overload_sheds_never_crashes(tmp_path):
    """Flooding past the watermark rejects with pinned envelopes and
    metrics — no exception, no step_failed, queue capped — and the same
    flood under a seeded probabilistic admission fault plan sheds
    IDENTICALLY on replay (satellite: shed determinism under faults)."""
    exp = make_exp(tmp_path, "exp")

    def flood(sroot, with_faults):
        if with_faults:
            faults.install(faults.FaultPlan([
                faults.FaultSpec(site="admission", kind="io_error",
                                 times=99, probability=0.5),
            ], seed=7))
        else:
            faults.clear()
        for i in range(8):
            serve.enqueue_job(sroot, spec(
                f"f-{i}", exp.root, submitted_at=float(i)))
        # one bad spec rides along: must reject, not crash the scan
        (serve.spool_dir(sroot, "incoming") / "bad.json").write_text("{not")
        daemon = serve.ServeDaemon(
            sroot, admission=AdmissionConfig(max_queue=3, tenant_quota=99),
            install_handlers=False)
        daemon._scan_incoming()  # must not raise
        rejected = {}
        for p in serve.spool_dir(sroot, "rejected").glob("*.json"):
            env = json.loads(p.read_text())
            rejected[p.stem] = (env["decision"]["reason"],
                                env["decision"]["retry_after_s"])
        return daemon, rejected

    daemon, rejected = flood(tmp_path / "s1", with_faults=False)
    assert daemon.queue.depth() == 3 and daemon.queue.shedding()
    assert rejected.pop("bad") == ("invalid_spec", 0.0)
    assert set(rejected.values()) == {("queue_full", 30.0)}
    assert len(rejected) == 5
    reg = telemetry.get_registry()
    assert reg.counter("tmx_serve_rejected_total", tenant="a",
                       reason="queue_full").value == 5
    assert reg.counter("tmx_serve_shed_total", tenant="a").value == 5
    events = RunLedger(serve.ledger_path(tmp_path / "s1")).events()
    assert sum(e.get("event") == "job_rejected" for e in events) == 6
    assert not any(e.get("event") == "step_failed" for e in events)

    # seeded fault plan: injected admission faults become pinned
    # admission_fault rejections, and two replays shed identically
    _, r1 = flood(tmp_path / "s2", with_faults=True)
    _, r2 = flood(tmp_path / "s3", with_faults=True)
    assert r1 == r2
    assert ("admission_fault", 10.0) in r1.values()
    assert all(reason in ("admission_fault", "queue_full", "invalid_spec")
               for reason, _ in r1.values())


def test_serve_deadline_expires_mid_run(tmp_path):
    """An admitted job whose deadline passes mid-run is cancelled at the
    next batch boundary: partial outputs persist, the job lands in
    spool/expired, and the daemon keeps serving (exit 0)."""
    sroot = tmp_path / "srv"
    exp = make_exp(tmp_path, "exp")
    ServeDummy.SLEEP = 0.1
    serve.enqueue_job(sroot, spec(
        "late-1", exp.root, deadline=time.time() + 0.15))
    rc = serve.run_serve(sroot, poll_s=0.01, max_jobs=1,
                         install_handlers=False)
    assert rc == 0
    env = json.loads(
        (serve.spool_dir(sroot, "expired") / "late-1.json").read_text())
    assert env["reason"] == "deadline"
    events = RunLedger(serve.ledger_path(sroot)).events()
    assert any(e.get("event") == "job_expired" and e.get("job") == "late-1"
               for e in events)
    # cancelled at a batch boundary, not mid-write: every marker that
    # exists is complete, and not all of them ran
    outs = dummy_outputs(exp)
    assert all(v == f"payload-{int(k[4:7])}" for k, v in outs.items())
    assert len(outs) < ServeDummy.N_BATCHES
    assert telemetry.get_registry().counter(
        "tmx_serve_deadline_expired_total", tenant="a").value == 1


def test_serve_sigterm_drain_restart_converges(tmp_path):
    """THE chaos convergence proof: SIGTERM mid-job drains the engine,
    re-spools every admitted-but-unfinished job, exits 75; a restarted
    daemon resumes and the final outputs are bit-identical to clean
    direct runs — a preemption is routine, not an outage."""
    sroot = tmp_path / "srv"
    exp_a = make_exp(tmp_path, "expa")
    exp_b = make_exp(tmp_path, "expb")
    serve.enqueue_job(sroot, spec("a-1", exp_a.root, tenant="a"))
    serve.enqueue_job(sroot, spec("b-1", exp_b.root, tenant="b"))
    faults.install(faults.FaultPlan([
        faults.FaultSpec(site="batch_run", kind="sigterm",
                         step="servedummy", batch=1),
    ]))
    rc = serve.run_serve(sroot, poll_s=0.01, install_handlers=True)
    assert rc == EXIT_PREEMPTED
    # both jobs back in incoming/ (interrupted + queued), none lost
    assert sorted(p.stem for p in serve.spool_dir(sroot, "incoming")
                  .glob("*.json")) == ["a-1", "b-1"]
    assert not list(serve.spool_dir(sroot, "admitted").glob("*.json"))
    events = RunLedger(serve.ledger_path(sroot)).events()
    pre = [e for e in events if e.get("event") == "serve_preempted"]
    assert len(pre) == 1 and pre[0]["requeued"] == 2
    assert sum(e.get("event") == "job_requeued" for e in events) == 2

    # restart: recovery + resume, both jobs complete
    faults.clear()
    resilience.clear_preemption()
    rc = serve.run_serve(sroot, poll_s=0.01, max_jobs=2,
                         install_handlers=True)
    assert rc == 0
    assert sorted(p.stem for p in serve.spool_dir(sroot, "done")
                  .glob("*.json")) == ["a-1", "b-1"]

    # convergence: bit-identical to clean direct sequential runs
    ref = make_exp(tmp_path, "ref")
    Workflow(ref, dummy_description()).run()
    assert dummy_outputs(exp_a) == dummy_outputs(ref)
    assert dummy_outputs(exp_b) == dummy_outputs(ref)
    # and no duplicated batches in either job ledger
    for exp in (exp_a, exp_b):
        done = [e["batch"]
                for e in RunLedger(exp.workflow_dir / "ledger.jsonl").events()
                if e.get("event") == "batch_done"]
        assert sorted(done) == list(range(ServeDummy.N_BATCHES))


def test_serve_hard_crash_recovery_respools_admitted(tmp_path):
    """Startup recovery is the crash-consistent counterpart of the
    SIGTERM drain: jobs a dead daemon left in admitted/ re-spool to
    incoming/ and run to completion."""
    sroot = tmp_path / "srv"
    exp = make_exp(tmp_path, "exp")
    serve.ensure_layout(sroot)
    # simulate a daemon that died after admitting but before running
    from tmlibrary_tpu.atomicio import atomic_write_json
    atomic_write_json(serve.spool_dir(sroot, "admitted") / "a-1.json",
                      spec("a-1", exp.root).to_dict())
    rc = serve.run_serve(sroot, poll_s=0.01, max_jobs=1,
                         install_handlers=False)
    assert rc == 0
    assert [p.stem for p in serve.spool_dir(sroot, "done")
            .glob("*.json")] == ["a-1"]
    events = RunLedger(serve.ledger_path(sroot)).events()
    rec = [e for e in events if e.get("event") == "job_requeued"
           and e.get("phase") == "recovery"]
    assert len(rec) == 1
    started = [e for e in events if e.get("event") == "serve_started"]
    assert started[0]["recovered"] == 1


def test_enqueue_fault_site_fails_cleanly(tmp_path, capsys):
    """An injected enqueue fault surfaces as a CLI error (exit 1), never
    a traceback or a half-written spec in the spool."""
    from tmlibrary_tpu.cli import main

    sroot = tmp_path / "srv"
    exp = make_exp(tmp_path, "exp")
    faults.install(faults.FaultPlan([
        faults.FaultSpec(site="enqueue", kind="io_error", step="a"),
    ]))
    assert main(["enqueue", "--root", str(sroot),
                 "--experiment", str(exp.root),
                 "--tenant", "a", "--job-id", "a-1"]) == 1
    assert "enqueue failed" in capsys.readouterr().err
    assert not list(serve.spool_dir(sroot, "incoming").glob("*.json"))
    # the fault burned its one shot; the retry lands
    assert main(["enqueue", "--root", str(sroot),
                 "--experiment", str(exp.root),
                 "--tenant", "a", "--job-id", "a-1"]) == 0


def test_top_dashboard_renders_serve_panel(tmp_path):
    """`tmx top` over a serve root grows a SERVE panel with queue bar,
    shedding flag and per-tenant rows."""
    from tmlibrary_tpu import top

    sroot = tmp_path / "srv"
    exp = make_exp(tmp_path, "exp")
    serve.enqueue_job(sroot, spec("a-1", exp.root, tenant="a"))
    rc = serve.run_serve(sroot, poll_s=0.01, max_jobs=1,
                         install_handlers=False)
    assert rc == 0
    view = top.collect_fleet(sroot)
    assert view["serve"] is not None
    text = top.render_dashboard(view)
    assert "serve" in text and "a" in text


# ============================================= cross-tenant coalescing
def test_cross_tenant_coalescing_no_recompile(tmp_path, rng):
    """Two tenants running the SAME pipeline content against different
    experiments share one compiled program: after tenant A's job primes
    the process-level caches, tenant B's job adds ZERO compiles to the
    perf profile (the acceptance metric behind keeping the daemon
    resident)."""
    import cv2

    from test_workflow import make_description

    from tmlibrary_tpu import perf

    src = tmp_path / "microscope"
    src.mkdir()
    for site in range(4):
        cv2.imwrite(str(src / f"A01_s{site}_DAPI.png"),
                    synth_site_image(rng))

    def make_real_exp(name):
        placeholder = Experiment(
            name=name, plates=[], channels=[], site_height=1, site_width=1
        )
        store = ExperimentStore.create(tmp_path / name, placeholder)
        desc = make_description(src, store)
        desc.save(store.workflow_dir / "workflow.yaml")
        return store

    def total_compiles():
        return sum(p.get("compiles", 0) for p in perf.perf_profiles())

    exp_a = make_real_exp("tenant_a")
    exp_b = make_real_exp("tenant_b")
    sroot = tmp_path / "srv"
    serve.enqueue_job(sroot, spec("a-1", exp_a.root, tenant="a"))
    assert serve.run_serve(sroot, poll_s=0.01, max_jobs=1,
                           install_handlers=False) == 0
    primed = total_compiles()

    serve.enqueue_job(sroot, spec("b-1", exp_b.root, tenant="b"))
    assert serve.run_serve(sroot, poll_s=0.01, max_jobs=1,
                           install_handlers=False) == 0
    assert total_compiles() == primed, (
        "tenant B's identical pipeline recompiled instead of coalescing")

    done = sorted(p.stem for p in serve.spool_dir(sroot, "done")
                  .glob("*.json"))
    assert done == ["a-1", "b-1"]
    # both tenants produced real features from their own stores
    for store in (exp_a, exp_b):
        feats = ExperimentStore.open(store.root).read_features("nuclei")
        assert len(feats) > 0


# ========================================== request-level observability
def test_serve_trace_id_links_enqueue_to_engine_phases(tmp_path, capsys):
    """Acceptance: one trace_id, stamped at enqueue, labels the serve
    ledger's lifecycle events AND every engine event in the job's own
    experiment ledger — and `tmx trace --export chrome` renders the
    whole chain (queue_wait → sched_delay → job → run/step/batch) as a
    schema-valid document reconstructed purely from ledgers."""
    from tmlibrary_tpu import traceexport
    from tmlibrary_tpu.cli import main

    sroot = tmp_path / "srv"
    exp = make_exp(tmp_path, "exp")
    assert main(["enqueue", "--root", str(sroot),
                 "--experiment", str(exp.root), "--tenant", "a",
                 "--job-id", "a-1", "--trace-id", "t-fixed"]) == 0
    assert "trace t-fixed" in capsys.readouterr().out
    assert serve.run_serve(sroot, poll_s=0.01, max_jobs=1,
                           install_handlers=False) == 0

    sevents = RunLedger(serve.ledger_path(sroot)).events()
    admitted = next(e for e in sevents if e.get("event") == "job_admitted")
    assert admitted["trace_id"] == "t-fixed"
    assert admitted["queue_wait_s"] >= 0.0
    started = next(e for e in sevents if e.get("event") == "job_started")
    assert started["trace_id"] == "t-fixed"
    assert started["sched_delay_s"] >= 0.0
    spans = {e["span"]: e for e in sevents if e.get("event") == "span"}
    assert {"queue_wait", "sched_delay", "job"} <= set(spans)
    for name in ("queue_wait", "sched_delay", "job"):
        assert spans[name]["trace_id"] == "t-fixed"
        assert spans[name]["tenant"] == "a"
    assert spans["job"]["attempt"] == 0

    # the engine's OWN ledger carries the same trace labels on every
    # event (RunLedger.append stamps the ambient scope)
    jevents = RunLedger(exp.workflow_dir / "ledger.jsonl").events()
    assert jevents and all(e.get("trace_id") == "t-fixed"
                           and e.get("job") == "a-1"
                           and e.get("tenant") == "a" for e in jevents)
    jspans = {e["span"] for e in jevents if e.get("event") == "span"}
    assert {"run", "step", "batch"} <= jspans

    # chrome export of just this trace, from ledgers alone
    out = tmp_path / "trace.json"
    assert main(["trace", "--root", str(sroot), "--export", "chrome",
                 str(out), "--trace-id", "t-fixed"]) == 0
    assert "wrote" in capsys.readouterr().out
    doc = json.loads(out.read_text())
    assert traceexport.validate_chrome_trace(doc) == []
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"queue_wait", "sched_delay", "job", "run", "step",
            "batch"} <= names
    flows = [e for e in doc["traceEvents"] if e["ph"] in ("s", "t", "f")]
    assert sorted(e["ph"] for e in flows) == ["f", "s", "t"]
    # the text view accepts the serve root too (satellite: serve-root
    # tmx trace) and honours the trace filter
    assert main(["trace", "--root", str(sroot),
                 "--trace-id", "t-fixed"]) == 0
    assert "job" in capsys.readouterr().out


def test_enqueue_generates_trace_id_when_not_given(tmp_path, capsys):
    from tmlibrary_tpu.cli import main

    sroot = tmp_path / "srv"
    exp = make_exp(tmp_path, "exp")
    assert main(["enqueue", "--root", str(sroot),
                 "--experiment", str(exp.root), "--tenant", "a",
                 "--job-id", "a-1"]) == 0
    assert "trace " in capsys.readouterr().out
    spec_path = serve.spool_dir(sroot, "incoming") / "a-1.json"
    stamped = JobSpec.from_dict(json.loads(spec_path.read_text()))
    assert stamped.trace_id and len(stamped.trace_id) == 32


def test_slo_burn_latched_warn_only(tmp_path, monkeypatch):
    """A sustained breach appends ONE slo_burn event per (tenant,
    window) episode — latched, warn-only, re-armed when the burn clears."""
    monkeypatch.setenv("TMX_SLO_AVAILABILITY", "0.99")
    monkeypatch.setenv("TMX_SLO_WINDOWS", "3600")
    sroot = tmp_path / "srv"
    daemon = serve.ServeDaemon(sroot, install_handlers=False)
    now = time.time()
    daemon.ledger.append(event="job_failed", job="a-1", tenant="a",
                         error="boom")
    # burn check is throttled; force it due
    daemon._last_slo_check = -1e9
    daemon._check_slo()
    daemon._last_slo_check = -1e9
    daemon._check_slo()  # still burning: must NOT append a second event
    events = RunLedger(serve.ledger_path(sroot)).events()
    burns = [e for e in events if e.get("event") == "slo_burn"]
    assert len(burns) == 1
    assert burns[0]["tenant"] == "a" and burns[0]["window"] == "3600"
    assert telemetry.get_registry().counter(
        "tmx_slo_burn_total", tenant="a", window="3600").value == 1
    # never a step_failed / abort — warn-only contract
    assert not any(e.get("event") == "step_failed" for e in events)

    # 100 fresh successes dilute the failure below burn=1: latch re-arms
    for i in range(100):
        daemon.ledger.append(event="job_done", job=f"ok-{i}", tenant="a",
                             elapsed_s=0.01)
    daemon._last_slo_check = -1e9
    daemon._check_slo()
    assert daemon._slo_latched == set()
    # a NEW breach episode warns again
    for i in range(100):
        daemon.ledger.append(event="job_failed", job=f"bad-{i}",
                             tenant="a", error="boom")
    daemon._last_slo_check = -1e9
    daemon._check_slo()
    burns = [e for e in RunLedger(serve.ledger_path(sroot)).events()
             if e.get("event") == "slo_burn"]
    assert len(burns) == 2
    assert now  # silence lint on the unused anchor


def test_serve_status_view_and_top_carry_slo_panel(tmp_path, capsys):
    """serve_status_view (and therefore `tmx top --once --json`) exposes
    the SLO report and per-tenant queue-wait quantiles; `tmx slo` renders
    and exits 0 at the default objectives."""
    from tmlibrary_tpu.cli import main

    sroot = tmp_path / "srv"
    exp = make_exp(tmp_path, "exp")
    serve.enqueue_job(sroot, spec("a-1", exp.root, tenant="a"))
    assert serve.run_serve(sroot, poll_s=0.01, max_jobs=1,
                           install_handlers=False) == 0

    view = serve.serve_status_view(sroot)
    assert view["slo"] is not None
    t = view["slo"]["tenants"]["a"]
    assert t["jobs"]["ok"] == 1 and t["breach"] is False
    assert view["queue_wait_s"]["a"]["n"] == 1
    assert view["queue_wait_s"]["a"]["p95"] >= 0.0

    assert main(["top", "--root", str(sroot), "--once", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["serve"]["slo"]["tenants"]["a"]["jobs"]["ok"] == 1
    assert "queue_wait_s" in doc["serve"]
    # and the rendered dashboard shows the slo row
    assert main(["top", "--root", str(sroot), "--once"]) == 0
    text = capsys.readouterr().out
    assert "slo a" in text and "burn" in text

    assert main(["slo", "--root", str(sroot)]) == 0
    out = capsys.readouterr().out
    assert "tenant a" in out and "burn 0.0" in out
    assert main(["slo", "--root", str(sroot), "--json"]) == 0
    jdoc = json.loads(capsys.readouterr().out)
    assert jdoc["tenants"]["a"]["jobs"]["total"] == 1
    # no serve ledger -> pinned no-data exit
    assert main(["slo", "--root", str(tmp_path / "nowhere")]) == 3
