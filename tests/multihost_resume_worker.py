"""Worker body for the host-death resume test (launched by
``tests/test_multihost_resume.py``, one subprocess per workflow phase).

Runs the full canonical workflow against a store the parent prepared on
disk.  Phase ``run`` is launched with ``TMX_FAULT_PLAN`` arming a
``kill`` fault at a jterator batch — the process hard-exits
(``os._exit(41)``) mid-step with no exception propagation and no
cleanup, leaving a partial run ledger exactly as a preempted worker
host would.  Phase ``resume`` re-launches against the same store with
no plan and ``resume=True``: it must reconstruct progress from the
ledger alone and finish only the remaining work.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    store_root, desc_path, phase = sys.argv[1], sys.argv[2], sys.argv[3]

    from tmlibrary_tpu.models.store import ExperimentStore
    from tmlibrary_tpu.workflow.engine import Workflow, WorkflowDescription

    store = ExperimentStore.open(store_root)
    desc = WorkflowDescription.load(desc_path)
    summary = Workflow(store, desc).run(resume=(phase == "resume"))
    print(f"WORKER_DONE phase={phase} steps={sorted(summary)}", flush=True)


if __name__ == "__main__":
    main()
