"""Cold-start elimination plane (``aotstore.py`` + perf.py hooks):
store round-trips, fingerprint/corruption loud-fallbacks, LRU pruning,
compile-ahead speculation, and the cross-process warm-start pin —
subprocess A compiles and exports, subprocess B imports with ZERO new
compiles and bit-identical features/labels.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tmlibrary_tpu import aotstore, perf, telemetry
from tmlibrary_tpu.capacity import likely_next_rungs

WORKER = os.path.join(os.path.dirname(__file__), "warmstart_worker.py")


@pytest.fixture
def store(tmp_path, monkeypatch):
    """Armed store in a fresh directory + fresh registry/profiles."""
    monkeypatch.setenv("TMX_AOT_STORE", "1")
    monkeypatch.setenv("TMX_AOT_STORE_DIR", str(tmp_path / "aot"))
    telemetry.reset_registry(enabled=True)
    perf.reset_profiles()
    aotstore.reset_counts()
    aotstore.reset_seconds_saved()
    yield str(tmp_path / "aot")
    telemetry.reset_registry()
    perf.reset_profiles()


def _compiled_toy():
    fn = jax.jit(lambda x: x * 2.0 + 1.0)
    x = jnp.arange(8, dtype=jnp.float32)
    return fn.lower(x).compile(), x


def _counter(name: str) -> float:
    return sum(c.get("value", 0.0)
               for c in telemetry.get_registry().snapshot()["counters"]
               if c.get("name") == name)


# ------------------------------------------------------------- round trip
def test_export_import_roundtrip(store):
    compiled, x = _compiled_toy()
    digest = aotstore.export_entry(
        compiled, program="toy", capacity=8, strategy="auto",
        signature="sig0", compile_s=0.5)
    assert digest is not None
    rows = aotstore.list_entries(store)
    assert len(rows) == 1 and rows[0]["digest"] == digest
    assert rows[0]["capacity"] == 8 and rows[0]["strategy"] == "auto"
    assert not rows[0]["stale"]

    hit = aotstore.import_entry(program="toy", capacity=8,
                                strategy="auto", signature="sig0")
    assert hit is not None
    compiled2, meta = hit
    np.testing.assert_array_equal(
        np.asarray(compiled2(x)), np.asarray(compiled(x)))
    assert meta["digest"] == digest
    assert aotstore.counts_snapshot() == {"export": 1.0, "import_hit": 1.0}
    assert aotstore.seconds_saved() == pytest.approx(0.5)


def test_import_misses_on_any_key_component(store):
    compiled, _ = _compiled_toy()
    aotstore.export_entry(compiled, program="toy", capacity=8,
                          strategy="auto", signature="sig0")
    for kw in ({"program": "other"}, {"capacity": 16},
               {"strategy": "sort"}, {"signature": "sig1"}):
        probe = {"program": "toy", "capacity": 8,
                 "strategy": "auto", "signature": "sig0", **kw}
        assert aotstore.import_entry(**probe) is None


def test_store_off_is_inert(store, monkeypatch):
    monkeypatch.setenv("TMX_AOT_STORE", "0")
    compiled, _ = _compiled_toy()
    assert aotstore.export_entry(compiled, program="toy",
                                 signature="s") is None
    assert aotstore.import_entry(program="toy", capacity=None,
                                 strategy=None, signature="s") is None
    assert aotstore.list_entries(store) == []


# ----------------------------------------------------- loud fallbacks
def test_fingerprint_mismatch_refuses_loudly(store, caplog):
    compiled, _ = _compiled_toy()
    digest = aotstore.export_entry(compiled, program="toy", capacity=8,
                                   strategy="auto", signature="sig0")
    meta_path = os.path.join(store, f"{digest}.json")
    meta = json.loads(open(meta_path).read())
    meta["fingerprint"] = "deadbeefdeadbeef"
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    with caplog.at_level("WARNING"):
        assert aotstore.import_entry(program="toy", capacity=8,
                                     strategy="auto",
                                     signature="sig0") is None
    assert any("fingerprint" in r.message for r in caplog.records)
    assert aotstore.counts_snapshot().get("import_hit", 0) == 0


def test_corrupt_artifact_falls_back_loudly_and_evicts(store, caplog):
    compiled, _ = _compiled_toy()
    digest = aotstore.export_entry(compiled, program="toy", capacity=8,
                                   strategy="auto", signature="sig0")
    with open(os.path.join(store, f"{digest}.bin"), "wb") as f:
        f.write(b"not a serialized executable")
    with caplog.at_level("WARNING"):
        assert aotstore.import_entry(program="toy", capacity=8,
                                     strategy="auto",
                                     signature="sig0") is None
    assert any("corrupt" in r.message.lower() for r in caplog.records)
    # the bad entry is evicted so every later lookup is a clean miss,
    # not a repeated deserialize failure
    assert aotstore.list_entries(store) == []


def test_stale_fingerprint_never_loads():
    # the fingerprint is INSIDE the entry digest: a store written by a
    # different jax/backend resolves to different file names, so a
    # stale artifact can never even be found
    a = aotstore.entry_digest("p", 8, "auto", "sig", fingerprint="aaaa")
    b = aotstore.entry_digest("p", 8, "auto", "sig", fingerprint="bbbb")
    assert a != b


# ------------------------------------------------------------- pruning
def test_prune_lru_cap_and_orphans(store):
    compiled, _ = _compiled_toy()
    digests = []
    for i in range(4):
        digests.append(aotstore.export_entry(
            compiled, program=f"p{i}", capacity=8, strategy="auto",
            signature="s"))
    # orphan payload with no meta sidecar
    with open(os.path.join(store, "feedface" * 5 + ".bin"), "wb") as f:
        f.write(b"x" * 64)
    per_entry = os.path.getsize(os.path.join(store, f"{digests[0]}.bin"))
    result = aotstore.prune(store, max_bytes=2 * per_entry + 1)
    assert result["kept"] == 2
    kept = {m["digest"] for m in aotstore.list_entries(store)}
    # LRU: the two most recent exports survive
    assert kept == set(digests[2:])
    assert not os.path.exists(os.path.join(store, "feedface" * 5 + ".bin"))


# ----------------------------------------------- speculation unit tests
def test_likely_next_rungs():
    ladder = (8, 16, 32, 64)
    assert likely_next_rungs(8, ladder) == (16,)
    assert likely_next_rungs(8, ladder, count=2) == (16, 32)
    assert likely_next_rungs(64, ladder) == ()
    # an observed peak above the next rung jumps speculation forward
    assert likely_next_rungs(8, ladder, observed=20) == (32,)
    assert likely_next_rungs(8, ladder, observed=3) == (16,)


def test_speculate_compile_then_warm_hit(store, monkeypatch):
    monkeypatch.setenv("TMX_AOT_SPECULATE", "1")
    calls = []

    def raw_fn(x):
        calls.append(1)
        return x + 1.0

    wrapped = perf.instrument_batch_fn(
        jax.jit(raw_fn), program="spec_toy", capacity=8, strategy="auto")
    x = jnp.arange(4, dtype=jnp.float32)
    abs_args, abs_kwargs = perf.abstract_args((x,), {})
    # skeleton args produce the same signature as real arrays → the
    # speculative compile is adopted for the real call
    assert perf.speculate_compile(wrapped, abs_args, abs_kwargs) == "compiled"
    assert _counter("tmx_perf_compiles_total") == 0  # not a critical-path compile
    assert aotstore.counts_snapshot().get("export") == 1

    out = wrapped(x)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.arange(1, 5, dtype=np.float32))
    assert _counter("tmx_compile_warm_total") == 1
    assert _counter("tmx_compile_cold_total") == 0
    assert _counter("tmx_perf_compiles_total") == 0
    # second speculation on a known signature is a no-op
    assert perf.speculate_compile(wrapped, abs_args, abs_kwargs) == "known"


def test_instrumented_call_imports_across_registry_reset(store):
    """The in-process proxy for a daemon restart: same store, fresh
    registry/profiles — the call imports instead of compiling."""
    x = jnp.arange(4, dtype=jnp.float32)
    wrapped = perf.instrument_batch_fn(
        jax.jit(lambda v: v * 3.0), program="restart_toy", capacity=8,
        strategy="auto")
    first = np.asarray(wrapped(x))
    assert _counter("tmx_compile_cold_total") == 1
    assert _counter("tmx_compile_export_total") == 1

    # "restart": drop every in-process cache, keep the store
    # (reset_profiles also clears the _RUNTIME executable cache)
    telemetry.reset_registry(enabled=True)
    perf.reset_profiles()
    aotstore.reset_counts()
    wrapped2 = perf.instrument_batch_fn(
        jax.jit(lambda v: v * 3.0), program="restart_toy", capacity=8,
        strategy="auto")
    second = np.asarray(wrapped2(x))
    np.testing.assert_array_equal(first, second)
    assert _counter("tmx_compile_import_hit_total") == 1
    assert _counter("tmx_perf_compiles_total") == 0
    assert _counter("tmx_compile_cold_total") == 0


# ------------------------------------------- cross-process warm start
def test_cross_process_warmstart_bit_identical(store, tmp_path):
    """Subprocess A cold-compiles both bucket rungs (a mid-ladder rung
    and the single-bucket ceiling) and exports; subprocess B against the
    same store imports both with ZERO new compiles and byte-identical
    features/labels."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "TMX_AOT_STORE": "1",
        "TMX_AOT_STORE_DIR": store,
        "TMX_AOT_SPECULATE": "0",
        # pure-XLA ops: host-callback (pure_callback) programs embed
        # process-local pointers and refuse to serialize on cpu
        "TMX_NATIVE": "0",
    })

    def run(tag):
        out_json = tmp_path / f"{tag}.json"
        out_npz = tmp_path / f"{tag}.npz"
        proc = subprocess.run(
            [sys.executable, WORKER, str(out_json), str(out_npz), "16,64"],
            env=env, capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        return json.loads(out_json.read_text()), np.load(out_npz)

    a, arrays_a = run("a")
    assert a["cold"] == 2 and a["export"] == 2 and a["import_hit"] == 0
    assert a["perf_compiles"] == 2
    assert a["store_entries"] == 2

    b, arrays_b = run("b")
    # THE pin: a fresh process against a warm store never compiles
    assert b["perf_compiles"] == 0
    assert b["cold"] == 0
    assert b["import_hit"] == 2
    assert b["seconds_saved"] > 0

    assert set(arrays_a.files) == set(arrays_b.files) and arrays_a.files
    for name in arrays_a.files:
        np.testing.assert_array_equal(arrays_a[name], arrays_b[name])
