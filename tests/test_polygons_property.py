"""Polygon tracing property tests: the traced ring must reconstruct the
object exactly (reference: MapobjectSegmentation polygons must cover the
same pixels the label image does)."""

import numpy as np
import pytest
import scipy.ndimage as ndi

from tmlibrary_tpu.ops.polygons import labels_to_polygons


def _blob_labels(rng, size=96, n=6):
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    img = np.zeros((size, size), np.float32)
    for _ in range(n):
        y, x = rng.integers(10, size - 10, 2)
        r = rng.uniform(3.0, 7.0)
        img += np.exp(-((yy - y) ** 2 + (xx - x) ** 2) / (2 * r**2))
    mask = ndi.binary_fill_holes(img > 0.4)
    lab, _ = ndi.label(mask, np.ones((3, 3)))
    return lab


@pytest.mark.parametrize("seed", range(5))
def test_traced_rings_reconstruct_objects(seed):
    import cv2

    rng = np.random.default_rng(5000 + seed)
    labels = _blob_labels(rng)
    polys = dict(labels_to_polygons(labels))
    ids = sorted(np.unique(labels[labels > 0]))
    assert sorted(polys) == [int(i) for i in ids]

    for lab in ids:
        want = labels == lab
        ring = polys[int(lab)]
        # ring vertices must all be boundary pixels of the object
        on_obj = want[ring[:, 0], ring[:, 1]]
        assert on_obj.all(), f"seed={seed} label={lab}: vertex off object"
        # fill the closed ring: must reconstruct the object EXACTLY
        # (objects here are simply connected by construction)
        got = np.zeros_like(want, np.uint8)
        cv2.fillPoly(got, [ring[:, ::-1].reshape(-1, 1, 2)], 1)
        np.testing.assert_array_equal(
            got.astype(bool), want,
            err_msg=f"seed={seed} label={lab}: ring does not reconstruct",
        )


def test_cv2_fallback_reconstructs_too(monkeypatch):
    """The cv2 border-following fallback (no native lib) must satisfy the
    same reconstruction property."""
    import cv2

    from tmlibrary_tpu import native

    monkeypatch.setattr(native, "available", lambda: False)
    rng = np.random.default_rng(42)
    labels = _blob_labels(rng)
    polys = dict(labels_to_polygons(labels))
    for lab, ring in polys.items():
        want = labels == lab
        got = np.zeros_like(want, np.uint8)
        cv2.fillPoly(got, [ring[:, ::-1].reshape(-1, 1, 2)], 1)
        np.testing.assert_array_equal(got.astype(bool), want)
