"""Build hook: compile the first-party C++ host kernels into the package.

``native/tmnative.cpp`` (union-find CC labeling, Moore boundary tracing,
bounding boxes, convex hulls) is a plain ctypes shared library, not a
CPython extension — so instead of Extension/build_ext machinery it is
compiled with the ambient C++ compiler and shipped as package data
(``tmlibrary_tpu/libtmnative.so``).  ``tmlibrary_tpu.native`` searches the
package directory first, then the source tree, and can rebuild from source
at import time, so editable installs and compiler-less environments both
keep working (every native entry point has a scipy/numpy fallback).
"""

import shutil
import subprocess
from pathlib import Path

from setuptools import setup
from setuptools.command.build_py import build_py

ROOT = Path(__file__).resolve().parent


class BuildWithNative(build_py):
    def run(self):
        super().run()
        src = ROOT / "native" / "tmnative.cpp"
        if not src.exists() or shutil.which("g++") is None:
            return  # fallbacks cover the native layer's absence
        out_dir = Path(self.build_lib) / "tmlibrary_tpu"
        out_dir.mkdir(parents=True, exist_ok=True)
        so = out_dir / "libtmnative.so"
        try:
            subprocess.run(
                ["g++", "-O3", "-ffp-contract=off", "-fPIC", "-std=c++17", "-shared",
                 "-o", str(so), str(src)],
                check=True, timeout=300,
            )
        except subprocess.SubprocessError:
            pass  # ship without the .so; runtime auto-build/fallback applies


setup(cmdclass={"build_py": BuildWithNative})
