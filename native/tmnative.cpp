// tmnative: first-party native host kernels.
//
// Reference parity: the reference's performance-critical host code lives in
// third-party C++ (cv2, mahotas — SURVEY.md §3 "external binary deps"); the
// TPU rebuild keeps device math in XLA and implements its own native host
// kernels for the two pathways that stay on the CPU:
//
//   1. union-find connected-component labeling (scipy scan order) — the
//      host-side golden/fallback for the device labeler and the fast path
//      for host-only workflows (ingest QC, tests);
//   2. Moore-neighbor boundary tracing — polygon extraction for the object
//      store (reference: PostGIS polygons via shapely/cv2).
//
// Built as a plain shared library, loaded via ctypes (no pybind11 in the
// image). C ABI only.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <array>
#include <cstring>
#include <limits>
#include <utility>
#include <vector>

namespace {

struct UnionFind {
  std::vector<int32_t> parent;
  explicit UnionFind(size_t n) : parent(n) {
    for (size_t i = 0; i < n; ++i) parent[i] = static_cast<int32_t>(i);
  }
  int32_t find(int32_t x) {
    while (parent[x] != x) {
      parent[x] = parent[parent[x]];  // path halving
      x = parent[x];
    }
    return x;
  }
  void unite(int32_t a, int32_t b) {
    a = find(a);
    b = find(b);
    if (a == b) return;
    // keep the smaller root: scan-order labeling falls out of this
    if (a < b) parent[b] = a; else parent[a] = b;
  }
};

}  // namespace

extern "C" {

// Label the foreground (mask != 0) with 4- or 8-connectivity.
// labels_out receives 0 for background, 1..N in scipy scan order
// (components numbered by first pixel in row-major order).
// Returns N, or -1 on invalid arguments.
int32_t tm_cc_label(const uint8_t* mask, int32_t h, int32_t w,
                    int32_t connectivity, int32_t* labels_out) {
  if (!mask || !labels_out || h <= 0 || w <= 0) return -1;
  if (connectivity != 4 && connectivity != 8) return -1;
  const size_t n = static_cast<size_t>(h) * static_cast<size_t>(w);
  UnionFind uf(n);

  // one pass of neighbor unions (only look up/left — prior pixels)
  for (int32_t y = 0; y < h; ++y) {
    for (int32_t x = 0; x < w; ++x) {
      const size_t i = static_cast<size_t>(y) * w + x;
      if (!mask[i]) continue;
      if (x > 0 && mask[i - 1]) uf.unite(static_cast<int32_t>(i), static_cast<int32_t>(i - 1));
      if (y > 0) {
        const size_t up = i - w;
        if (mask[up]) uf.unite(static_cast<int32_t>(i), static_cast<int32_t>(up));
        if (connectivity == 8) {
          if (x > 0 && mask[up - 1]) uf.unite(static_cast<int32_t>(i), static_cast<int32_t>(up - 1));
          if (x + 1 < w && mask[up + 1]) uf.unite(static_cast<int32_t>(i), static_cast<int32_t>(up + 1));
        }
      }
    }
  }

  // second pass: roots are component minima (smaller-root union), so
  // numbering roots in scan order reproduces scipy.ndimage.label exactly
  std::vector<int32_t> remap(n, 0);
  int32_t next = 0;
  for (size_t i = 0; i < n; ++i) {
    if (!mask[i]) { labels_out[i] = 0; continue; }
    const int32_t r = uf.find(static_cast<int32_t>(i));
    if (remap[r] == 0) remap[r] = ++next;
    labels_out[i] = remap[r];
  }
  return next;
}

// Moore-neighbor boundary trace of one labeled object (8-connected
// boundary, clockwise, starting at the first pixel in scan order).
// out_yx receives up to max_pts (y, x) pairs; returns the number of
// points, 0 if the label is absent, or -1 on invalid arguments.
int32_t tm_trace_boundary(const int32_t* labels, int32_t h, int32_t w,
                          int32_t label, int32_t* out_yx, int32_t max_pts) {
  if (!labels || !out_yx || h <= 0 || w <= 0 || max_pts <= 0) return -1;
  auto at = [&](int32_t y, int32_t x) -> bool {
    return y >= 0 && y < h && x >= 0 && x < w &&
           labels[static_cast<size_t>(y) * w + x] == label;
  };
  // first pixel in scan order
  int32_t sy = -1, sx = -1;
  for (int32_t y = 0; y < h && sy < 0; ++y)
    for (int32_t x = 0; x < w; ++x)
      if (at(y, x)) { sy = y; sx = x; break; }
  if (sy < 0) return 0;

  // clockwise Moore neighborhood order: W, NW, N, NE, E, SE, S, SW
  static const int32_t dy[8] = {0, -1, -1, -1, 0, 1, 1, 1};
  static const int32_t dx[8] = {-1, -1, 0, 1, 1, 1, 0, -1};

  // Moore tracing with explicit backtrack + Jacob's stopping criterion:
  // stop when the start pixel is re-entered from its original backtrack.
  int32_t cy = sy, cx = sx;
  int32_t back = 0;  // direction from current to backtrack; start = west
  const int32_t back0 = back;
  int32_t count = 0;
  const int64_t limit = static_cast<int64_t>(h) * w * 4 + 8;
  for (int64_t iter = 0; iter < limit; ++iter) {
    if (iter == 0 || !(cy == sy && cx == sx)) {
      if (count < max_pts) {
        out_yx[2 * count] = cy;
        out_yx[2 * count + 1] = cx;
      }
      ++count;
    }
    // scan clockwise from just past the backtrack neighbor
    int32_t k = 1;
    int32_t d = -1;
    for (; k <= 8; ++k) {
      d = (back + k) % 8;
      if (at(cy + dy[d], cx + dx[d])) break;
    }
    if (k > 8) break;  // isolated pixel
    // move; the new backtrack is the neighbor scanned just before d,
    // expressed as a direction from the NEW pixel
    const int32_t prev = (back + k - 1) % 8;
    const int32_t py = cy + dy[prev], px = cx + dx[prev];
    cy += dy[d];
    cx += dx[d];
    // direction from new current back to that previous (background) pixel
    back = 0;
    for (int32_t j = 0; j < 8; ++j) {
      if (cy + dy[j] == py && cx + dx[j] == px) { back = j; break; }
    }
    if (cy == sy && cx == sx && back == back0) break;
  }
  // return the TRUE count even when it exceeds max_pts, so callers can
  // detect truncation and retry with a larger buffer
  return count;
}

// Per-object bounding boxes: out receives (min_y, min_x, max_y, max_x) per
// label 1..max_label (rows of 4); labels absent get (-1,-1,-1,-1).
void tm_bounding_boxes(const int32_t* labels, int32_t h, int32_t w,
                       int32_t max_label, int32_t* out) {
  for (int32_t l = 0; l < max_label; ++l) {
    out[4 * l] = -1; out[4 * l + 1] = -1; out[4 * l + 2] = -1; out[4 * l + 3] = -1;
  }
  for (int32_t y = 0; y < h; ++y) {
    for (int32_t x = 0; x < w; ++x) {
      const int32_t v = labels[static_cast<size_t>(y) * w + x];
      if (v < 1 || v > max_label) continue;
      int32_t* b = out + 4 * (v - 1);
      if (b[0] < 0) { b[0] = y; b[1] = x; b[2] = y; b[3] = x; }
      else {
        if (y < b[0]) b[0] = y;
        if (x < b[1]) b[1] = x;
        if (y > b[2]) b[2] = y;
        if (x > b[3]) b[3] = x;
      }
    }
  }
}

// Per-object rasterized convex hull pixel counts (skimage
// convex_hull_image semantics over pixel centers): for each label
// 1..max_label, out[l-1] receives the number of pixels whose center lies
// inside or on the convex hull of the object's pixel centers.  Labels
// absent get 0.  Solidity = area / hull_count falls out on the caller
// side.  Returns 0, or -1 on invalid arguments.
int32_t tm_hull_pixel_counts(const int32_t* labels, int32_t h, int32_t w,
                             int32_t max_label, int32_t* out) {
  if (!labels || !out || h <= 0 || w <= 0 || max_label <= 0) return -1;
  std::memset(out, 0, sizeof(int32_t) * static_cast<size_t>(max_label));

  // gather per-label bounding boxes + pixel lists in one scan
  std::vector<int32_t> bbox(static_cast<size_t>(max_label) * 4);
  for (int32_t l = 0; l < max_label; ++l) {
    bbox[4 * l] = -1; bbox[4 * l + 1] = -1; bbox[4 * l + 2] = -1; bbox[4 * l + 3] = -1;
  }
  std::vector<std::vector<std::pair<int32_t, int32_t>>> pts(max_label);
  for (int32_t y = 0; y < h; ++y) {
    for (int32_t x = 0; x < w; ++x) {
      const int32_t v = labels[static_cast<size_t>(y) * w + x];
      if (v < 1 || v > max_label) continue;
      int32_t* b = &bbox[4 * (v - 1)];
      if (b[0] < 0) { b[0] = y; b[1] = x; b[2] = y; b[3] = x; }
      else {
        if (y < b[0]) b[0] = y;
        if (x < b[1]) b[1] = x;
        if (y > b[2]) b[2] = y;
        if (x > b[3]) b[3] = x;
      }
      pts[v - 1].emplace_back(x, y);
    }
  }

  auto cross = [](int64_t ox, int64_t oy, int64_t ax, int64_t ay,
                  int64_t bx, int64_t by) -> int64_t {
    return (ax - ox) * (by - oy) - (ay - oy) * (bx - ox);
  };

  for (int32_t l = 0; l < max_label; ++l) {
    auto& p = pts[l];
    const size_t n = p.size();
    if (n == 0) continue;
    if (n <= 2) { out[l] = static_cast<int32_t>(n); continue; }
    // Andrew's monotone chain (points are already sorted by (y, x) from the
    // scan; re-sort by (x, y) as the algorithm expects)
    std::sort(p.begin(), p.end());
    std::vector<std::pair<int32_t, int32_t>> hull(2 * n);
    size_t k = 0;
    for (size_t i = 0; i < n; ++i) {            // lower hull
      while (k >= 2 && cross(hull[k - 2].first, hull[k - 2].second,
                             hull[k - 1].first, hull[k - 1].second,
                             p[i].first, p[i].second) <= 0) --k;
      hull[k++] = p[i];
    }
    for (size_t i = n - 1, t = k + 1; i-- > 0;) {  // upper hull
      while (k >= t && cross(hull[k - 2].first, hull[k - 2].second,
                             hull[k - 1].first, hull[k - 1].second,
                             p[i].first, p[i].second) <= 0) --k;
      hull[k++] = p[i];
    }
    hull.resize(k - 1);  // last point == first point
    const size_t m = hull.size();
    if (m <= 2) {  // degenerate (collinear object): hull pixels = object pixels
      out[l] = static_cast<int32_t>(n);
      continue;
    }
    // hull is counter-clockwise in (x, y) with cross<=0 popped: a pixel
    // center is inside-or-on iff it is left of (cross >= 0) every edge
    const int32_t* b = &bbox[4 * l];
    int32_t count = 0;
    for (int32_t y = b[0]; y <= b[2]; ++y) {
      for (int32_t x = b[1]; x <= b[3]; ++x) {
        bool inside = true;
        for (size_t i = 0; i < m && inside; ++i) {
          const auto& a0 = hull[i];
          const auto& a1 = hull[(i + 1) % m];
          if (cross(a0.first, a0.second, a1.first, a1.second, x, y) < 0)
            inside = false;
        }
        if (inside) ++count;
      }
    }
    out[l] = count;
  }
  return 0;
}

// Douglas-Peucker simplification of a closed (y, x) contour ring.
// pts: n rows of (y, x); keep: n flags (out), 1 = vertex survives.
// tol: perpendicular-distance tolerance in pixels.  The ring is split at
// vertex 0 and its farthest vertex (both always kept) so the closing
// edge is simplified like any other.  Returns the number of kept
// vertices, or -1 on invalid arguments.
int32_t tm_simplify_polygon(const int32_t* pts, int32_t n, double tol,
                            uint8_t* keep) {
  if (!pts || !keep || n < 0) return -1;
  std::memset(keep, 0, static_cast<size_t>(n));
  if (n <= 2) {
    for (int32_t i = 0; i < n; ++i) keep[i] = 1;
    return n;
  }
  const double tol2 = tol * tol;
  auto px = [&](int32_t i) { return static_cast<double>(pts[2 * i + 1]); };
  auto py = [&](int32_t i) { return static_cast<double>(pts[2 * i]); };

  // squared perpendicular distance of vertex i to chord (a, b)
  auto dist2 = [&](int32_t i, int32_t a, int32_t b) {
    const double ax = px(a), ay = py(a), bx = px(b), by = py(b);
    const double dx = bx - ax, dy = by - ay;
    const double len2 = dx * dx + dy * dy;
    if (len2 == 0.0) {
      const double ex = px(i) - ax, ey = py(i) - ay;
      return ex * ex + ey * ey;
    }
    const double cross = dx * (py(i) - ay) - dy * (px(i) - ax);
    return cross * cross / len2;
  };

  // split the ring at the vertex farthest from vertex 0
  int32_t far_i = 1;
  double far_d = -1.0;
  for (int32_t i = 1; i < n; ++i) {
    const double ex = px(i) - px(0), ey = py(i) - py(0);
    const double d = ex * ex + ey * ey;
    if (d > far_d) { far_d = d; far_i = i; }
  }
  keep[0] = 1;
  keep[far_i] = 1;

  // iterative DP over index ranges [a, b] (wrapping handled by the two
  // half-open arcs 0..far_i and far_i..n-1..(0))
  std::vector<std::pair<int32_t, int32_t>> stack;
  stack.emplace_back(0, far_i);
  stack.emplace_back(far_i, n);  // b == n means "chord ends at vertex 0"
  while (!stack.empty()) {
    const auto [a, b] = stack.back();
    stack.pop_back();
    const int32_t chord_b = (b == n) ? 0 : b;
    int32_t worst = -1;
    double worst_d = tol2;
    for (int32_t i = a + 1; i < b; ++i) {
      const double d = dist2(i, a, chord_b);
      if (d > worst_d) { worst_d = d; worst = i; }
    }
    if (worst >= 0) {
      keep[worst] = 1;
      stack.emplace_back(a, worst);
      stack.emplace_back(worst, b);
    }
  }
  int32_t kept = 0;
  for (int32_t i = 0; i < n; ++i) kept += keep[i];
  return kept;
}

}  // extern "C"


// ---------------------------------------------------------------------------
// Minimal TIFF reader: the native data-loader for imextract.
//
// Reference parity: the reference's image ingest leans on Bio-Formats (Java)
// and cv2 (C++) for plane decoding (SURVEY.md §3 readers row); this is the
// first-party replacement covering the formats microscopes actually emit as
// plain TIFF: classic little/big-endian TIFF, strip-organized, grayscale
// 8/16-bit, uncompressed / LZW (with horizontal predictor) / PackBits,
// multi-page.  Anything else returns an error and the Python caller falls
// back to cv2.
// ---------------------------------------------------------------------------

#include <cstdio>

namespace tifflite {

struct Buf {
  std::vector<uint8_t> d;
  bool le = true;
  uint16_t rd16(size_t o) const {
    if (o + 2 > d.size()) return 0;
    return le ? (uint16_t)(d[o] | (d[o + 1] << 8))
              : (uint16_t)((d[o] << 8) | d[o + 1]);
  }
  uint32_t rd32(size_t o) const {
    if (o + 4 > d.size()) return 0;
    return le ? ((uint32_t)d[o] | ((uint32_t)d[o + 1] << 8) |
                 ((uint32_t)d[o + 2] << 16) | ((uint32_t)d[o + 3] << 24))
              : (((uint32_t)d[o] << 24) | ((uint32_t)d[o + 1] << 16) |
                 ((uint32_t)d[o + 2] << 8) | (uint32_t)d[o + 3]);
  }
};

static bool load_file(const char* path, Buf& b) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  // reject non-TIFF from the 4-byte header BEFORE slurping the file, so a
  // PNG handed to the reader costs 4 bytes of IO, not a full read
  uint8_t hdr[4];
  if (std::fread(hdr, 1, 4, f) != 4) { std::fclose(f); return false; }
  if (hdr[0] == 'I' && hdr[1] == 'I') b.le = true;
  else if (hdr[0] == 'M' && hdr[1] == 'M') b.le = false;
  else { std::fclose(f); return false; }
  uint16_t magic = b.le ? (uint16_t)(hdr[2] | (hdr[3] << 8))
                        : (uint16_t)((hdr[2] << 8) | hdr[3]);
  if (magic != 42) { std::fclose(f); return false; }  // classic TIFF only
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  if (sz <= 8) { std::fclose(f); return false; }
  std::fseek(f, 0, SEEK_SET);
  b.d.resize((size_t)sz);
  size_t got = std::fread(b.d.data(), 1, (size_t)sz, f);
  std::fclose(f);
  return got == (size_t)sz;
}

// cap on IFD-chain walks: bounds page counts AND terminates on cyclic
// next-IFD pointers in corrupt/malicious files
constexpr int32_t kMaxPages = 65535;

struct Entry { uint16_t type; uint32_t count; size_t value_off; };

// value_off points at the 4-byte value field itself; values larger than
// 4 bytes live at the offset stored there.
static size_t entry_data(const Buf& b, const Entry& e, size_t elem_size) {
  size_t total = (size_t)e.count * elem_size;
  return total <= 4 ? e.value_off : (size_t)b.rd32(e.value_off);
}

static uint32_t entry_int(const Buf& b, const Entry& e, uint32_t idx) {
  size_t elem = e.type == 3 ? 2 : 4;  // SHORT or LONG
  size_t base = entry_data(b, e, elem);
  return elem == 2 ? b.rd16(base + 2 * idx) : b.rd32(base + 4 * idx);
}

struct IFD {
  uint32_t width = 0, height = 0, bits = 0, compression = 1;
  uint32_t samples = 1, rows_per_strip = 0xFFFFFFFFu, predictor = 1;
  std::vector<size_t> strip_offsets, strip_counts;
};

static bool parse_ifd(const Buf& b, size_t off, IFD& out, size_t* next) {
  if (off == 0 || off + 2 > b.d.size()) return false;
  uint16_t n = b.rd16(off);
  size_t p = off + 2;
  if (p + 12 * (size_t)n + 4 > b.d.size()) return false;
  Entry so{0, 0, 0}, sc{0, 0, 0};
  for (uint16_t i = 0; i < n; ++i, p += 12) {
    uint16_t tag = b.rd16(p);
    Entry e{b.rd16(p + 2), b.rd32(p + 4), p + 8};
    switch (tag) {
      case 256: out.width = entry_int(b, e, 0); break;
      case 257: out.height = entry_int(b, e, 0); break;
      case 258: out.bits = entry_int(b, e, 0); break;
      case 259: out.compression = entry_int(b, e, 0); break;
      case 273: so = e; break;
      case 277: out.samples = entry_int(b, e, 0); break;
      case 278: out.rows_per_strip = entry_int(b, e, 0); break;
      case 279: sc = e; break;
      case 317: out.predictor = entry_int(b, e, 0); break;
      default: break;
    }
  }
  *next = b.rd32(p);
  if (so.count == 0 || sc.count == 0 || so.count != sc.count) return false;
  for (uint32_t i = 0; i < so.count; ++i) {
    out.strip_offsets.push_back(entry_int(b, so, i));
    out.strip_counts.push_back(entry_int(b, sc, i));
  }
  return out.width > 0 && out.height > 0;
}

static bool lzw_decode(const uint8_t* src, size_t n, std::vector<uint8_t>& out,
                       size_t expect) {
  // TIFF LZW: MSB-first codes, 256=Clear, 257=EOI, early code-width
  // change.  Output-reference table: every code's expansion is a
  // substring of the ALREADY-DECODED output (entry next_free is the
  // previous emission plus the first byte of the current one — two
  // consecutive appends, so its bytes are contiguous in `out`), so each
  // entry stores (output offset, length) and emitting a string is ONE
  // memcpy from earlier output instead of a per-byte chain walk +
  // reverse (the chain-table form this replaces ran ~160 MB/s; the copy
  // form removes the O(length) pointer chase per code).
  uint32_t tpos[4096];
  uint32_t tlen[4096];
  int next_free = 258;
  // ONE up-front allocation sized expect + the largest possible single
  // emission (4095) + 8 bytes of chunked-copy overrun margin: the hot
  // loop then writes through a raw pointer with no growth checks, and
  // the 8-byte block copies below may read/write up to 7 bytes past a
  // string's end, always inside this buffer
  out.assign(expect + 4104, 0);
  uint8_t* o = out.data();
  size_t olen = 0;
  size_t pos = 0;
  uint64_t acc = 0;
  int nbits = 0;
  int width = 9;
  int prev = -1;
  uint32_t prev_pos = 0, prev_len = 0;
  while (olen < expect) {
    if (nbits < width) {  // bulk refill: ~once per several codes
      while (nbits <= 56 && pos < n) {
        acc = (acc << 8) | src[pos++];
        nbits += 8;
      }
      if (nbits < width) break;  // truncated stream
    }
    nbits -= width;
    int code = (int)((acc >> nbits) & ((1u << width) - 1));
    if (code == 257) break;  // EOI
    if (code == 256) {       // Clear
      next_free = 258;
      width = 9;
      prev = -1;
      continue;
    }
    const uint32_t at = (uint32_t)olen;
    uint32_t len;
    if (prev < 0) {
      // first code after Clear must be a literal
      if (code > 255) { out.resize(olen); return false; }
      o[olen++] = (uint8_t)code;
      prev = code;
      prev_pos = at;
      prev_len = 1;
      continue;
    }
    if (code < 256) {
      o[olen++] = (uint8_t)code;
      len = 1;
    } else if (code < next_free) {
      len = tlen[code];
      const uint8_t* s = o + tpos[code];
      uint8_t* d = o + at;
      if (at - tpos[code] >= 8) {
        // 8-byte chunks; the ≤7-byte tail overrun lands in dest bytes
        // the next emission (or the final resize) overwrites/discards
        for (uint32_t i = 0; i < len; i += 8) std::memcpy(d + i, s + i, 8);
      } else {  // source too close to dest for chunking (e.g. "ababab")
        for (uint32_t i = 0; i < len; ++i) d[i] = s[i];
      }
      olen += len;
    } else if (code == next_free) {
      // KwKwK: previous string + its own first byte
      len = prev_len + 1;
      const uint8_t* s = o + prev_pos;
      uint8_t* d = o + at;
      if (at - prev_pos >= 8) {
        for (uint32_t i = 0; i < prev_len; i += 8)
          std::memcpy(d + i, s + i, 8);
      } else {
        for (uint32_t i = 0; i < prev_len; ++i) d[i] = s[i];
      }
      d[prev_len] = s[0];
      olen += len;
    } else {
      out.resize(olen);
      return false;  // corrupt stream
    }
    if (next_free < 4096) {
      // previous emission [prev_pos, prev_pos+prev_len) is immediately
      // followed by this one, so the new entry's bytes are contiguous
      tpos[next_free] = prev_pos;
      tlen[next_free] = prev_len + 1;
      ++next_free;
    }
    // early change: width grows when the NEXT code would not fit
    if (next_free + 1 >= (1 << width) && width < 12) ++width;
    prev = code;
    prev_pos = at;
    prev_len = len;
  }
  out.resize(olen);
  return olen >= expect;
}

static bool packbits_decode(const uint8_t* src, size_t n,
                            std::vector<uint8_t>& out, size_t expect) {
  out.clear();
  out.reserve(expect);
  size_t i = 0;
  while (i < n && out.size() < expect) {
    int8_t c = (int8_t)src[i++];
    if (c >= 0) {
      size_t cnt = (size_t)c + 1;
      if (i + cnt > n) return false;
      out.insert(out.end(), src + i, src + i + cnt);
      i += cnt;
    } else if (c != -128) {
      if (i >= n) return false;
      out.insert(out.end(), (size_t)(1 - c), src[i++]);
    }
  }
  return out.size() >= expect;
}

// Walk to page `page`; -1 errors, else fills ifd.
static int walk(const Buf& b, int32_t page, IFD& ifd) {
  if (page >= kMaxPages) return -1;
  size_t off = b.rd32(4);
  for (int32_t i = 0; i < kMaxPages; ++i) {
    IFD cur;
    size_t next = 0;
    if (!parse_ifd(b, off, cur, &next)) return -1;
    if (i == page) { ifd = cur; return 0; }
    if (next == 0) return -1;
    off = next;
  }
  return -1;
}

}  // namespace tifflite

extern "C" {

// Raw TIFF-variant LZW strip decode (MSB-first codes, early width change)
// into a caller-sized buffer.  Exported for the Python container readers
// (Zeiss LSM strips are usually LZW) — the pure-Python bit-unpacking twin
// is ~100x slower on megabyte strips.  Returns 1 on success, 0 on corrupt
// input or short output.
int32_t tm_lzw_decode(const uint8_t* src, int64_t n, uint8_t* out,
                      int64_t expect) {
  if (!src || !out || n < 0 || expect < 0) return 0;
  std::vector<uint8_t> buf;
  if (!tifflite::lzw_decode(src, (size_t)n, buf, (size_t)expect)) return 0;
  std::memcpy(out, buf.data(), (size_t)expect);
  return 1;
}

// PackBits strip decode, same contract as tm_lzw_decode.
int32_t tm_packbits_decode(const uint8_t* src, int64_t n, uint8_t* out,
                           int64_t expect) {
  if (!src || !out || n < 0 || expect < 0) return 0;
  std::vector<uint8_t> buf;
  if (!tifflite::packbits_decode(src, (size_t)n, buf, (size_t)expect)) return 0;
  std::memcpy(out, buf.data(), (size_t)expect);
  return 1;
}

// out4: [n_pages, height, width, bits] of page 0.  Returns 0, or -1 when
// the file is not a TIFF this reader handles.
int32_t tm_tiff_info(const char* path, int32_t* out4) {
  if (!path || !out4) return -1;
  tifflite::Buf b;
  if (!tifflite::load_file(path, b)) return -1;
  tifflite::IFD first;
  size_t off = b.rd32(4), next = 0;
  if (!tifflite::parse_ifd(b, off, first, &next)) return -1;
  int32_t pages = 1;
  while (next != 0 && pages < tifflite::kMaxPages) {
    tifflite::IFD cur;
    size_t nn = 0;
    if (!tifflite::parse_ifd(b, next, cur, &nn)) break;
    ++pages;
    next = nn;
  }
  out4[0] = pages;
  out4[1] = (int32_t)first.height;
  out4[2] = (int32_t)first.width;
  out4[3] = (int32_t)first.bits;
  return 0;
}

// Decode grayscale page `page` into out (row-major uint16, h*w elements,
// 8-bit samples are widened).  Returns 0 on success; -1 on any
// parse/shape/unsupported-feature condition (caller falls back to cv2).
static int32_t tiff_decode_gray(const tifflite::Buf& b,
                                const tifflite::IFD& ifd, uint16_t* out,
                                int32_t h, int32_t w) {
  if (ifd.samples != 1) return -1;                    // grayscale only
  if (ifd.bits != 8 && ifd.bits != 16) return -1;
  if (ifd.predictor != 1 && ifd.predictor != 2) return -1;

  const size_t bytes_per_row = (size_t)w * (ifd.bits / 8);
  std::vector<uint8_t> plane;
  plane.reserve(bytes_per_row * (size_t)h);
  uint32_t rps = ifd.rows_per_strip ? ifd.rows_per_strip : (uint32_t)h;
  std::vector<uint8_t> strip;
  for (size_t s = 0; s < ifd.strip_offsets.size(); ++s) {
    uint32_t rows = rps;
    uint32_t row0 = (uint32_t)s * rps;
    if (row0 >= (uint32_t)h) break;
    if (row0 + rows > (uint32_t)h) rows = (uint32_t)h - row0;
    size_t expect = bytes_per_row * rows;
    size_t off = ifd.strip_offsets[s], cnt = ifd.strip_counts[s];
    if (off + cnt > b.d.size()) return -1;
    const uint8_t* src = b.d.data() + off;
    if (ifd.compression == 1) {
      if (cnt < expect) return -1;
      plane.insert(plane.end(), src, src + expect);
    } else if (ifd.compression == 5) {
      if (!tifflite::lzw_decode(src, cnt, strip, expect)) return -1;
      plane.insert(plane.end(), strip.begin(), strip.begin() + expect);
    } else if (ifd.compression == 32773) {
      if (!tifflite::packbits_decode(src, cnt, strip, expect)) return -1;
      plane.insert(plane.end(), strip.begin(), strip.begin() + expect);
    } else {
      return -1;  // unsupported codec
    }
  }
  if (plane.size() < bytes_per_row * (size_t)h) return -1;

  // samples -> uint16 with file byte order, then the horizontal predictor
  for (int32_t y = 0; y < h; ++y) {
    const uint8_t* row = plane.data() + (size_t)y * bytes_per_row;
    uint16_t* dst = out + (size_t)y * (size_t)w;
    if (ifd.bits == 8) {
      for (int32_t x = 0; x < w; ++x) dst[x] = row[x];
    } else {
      for (int32_t x = 0; x < w; ++x) {
        dst[x] = b.le ? (uint16_t)(row[2 * x] | (row[2 * x + 1] << 8))
                      : (uint16_t)((row[2 * x] << 8) | row[2 * x + 1]);
      }
    }
    if (ifd.predictor == 2) {
      // horizontal differencing accumulates in the SAMPLE width: 8-bit
      // samples wrap at 256, 16-bit at 65536
      if (ifd.bits == 8) {
        for (int32_t x = 1; x < w; ++x)
          dst[x] = (uint16_t)((dst[x] + dst[x - 1]) & 0xFF);
      } else {
        for (int32_t x = 1; x < w; ++x)
          dst[x] = (uint16_t)(dst[x] + dst[x - 1]);
      }
    }
  }
  return 0;
}

int32_t tm_tiff_read(const char* path, int32_t page, uint16_t* out,
                     int32_t h, int32_t w) {
  if (!path || !out || h <= 0 || w <= 0 || page < 0) return -1;
  tifflite::Buf b;
  if (!tifflite::load_file(path, b)) return -1;
  tifflite::IFD ifd;
  if (tifflite::walk(b, page, ifd) != 0) return -1;
  if ((int32_t)ifd.height != h || (int32_t)ifd.width != w) return -1;
  return tiff_decode_gray(b, ifd, out, h, w);
}

// Combined parse + decode in ONE file load: fills hw_out[0..2] with the
// page's height/width/bits and decodes into `out` when h*w fits
// `capacity` pixels.  Returns 0 on success, -2 when the capacity is too small
// (hw_out is still filled so the caller retries sized exactly), -1 on
// anything the paged reader does not handle.  Exists because the
// info-then-read protocol loaded and walked the file TWICE per page
// (~0.1 ms of the ~1 ms ingest cost per 256-px file).
int32_t tm_tiff_read2(const char* path, int32_t page, uint16_t* out,
                      int64_t capacity, int32_t* hw_out) {
  if (!path || !out || !hw_out || page < 0 || capacity < 0) return -1;
  tifflite::Buf b;
  if (!tifflite::load_file(path, b)) return -1;
  tifflite::IFD ifd;
  if (tifflite::walk(b, page, ifd) != 0) return -1;
  hw_out[0] = (int32_t)ifd.height;
  hw_out[1] = (int32_t)ifd.width;
  hw_out[2] = (int32_t)ifd.bits;
  if (ifd.height <= 0 || ifd.width <= 0) return -1;
  if ((int64_t)ifd.height * (int64_t)ifd.width > capacity) return -2;
  return tiff_decode_gray(b, ifd, out, (int32_t)ifd.height,
                          (int32_t)ifd.width);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// CPU-fallback segmentation kernels (round-3).
//
// When jax.default_backend() == "cpu" the XLA twins of the iterative
// segmentation ops (lax.while_loop fixpoints) are pathological — the
// round-2 bench lost to single-thread scipy 2.5:1 on that path.  These
// kernels are routed in via jax.pure_callback (ops/label.py,
// ops/segment_primary.py, ops/segment_secondary.py, method="native") and
// replicate the device semantics EXACTLY, including tie-breaking, so the
// bit-identical label gate holds across backends.

namespace wsnative {

// Neighbor geometry policies: the ONLY thing that differs between the
// 2-D and 3-D floods.
struct Geo2 {
  int32_t h, w, connectivity;

  template <typename Fn>
  void for_neighbors(int32_t i, Fn fn) const {
    const int32_t y = i / w, x = i % w;
    if (x > 0) fn(i - 1);
    if (x + 1 < w) fn(i + 1);
    if (y > 0) fn(i - w);
    if (y + 1 < h) fn(i + w);
    if (connectivity == 8) {
      if (y > 0 && x > 0) fn(i - w - 1);
      if (y > 0 && x + 1 < w) fn(i - w + 1);
      if (y + 1 < h && x > 0) fn(i + w - 1);
      if (y + 1 < h && x + 1 < w) fn(i + w + 1);
    }
  }
};

// full 26-neighborhood (ops/volume.py _adopt_step_3d always uses it)
struct Geo3 {
  int32_t nz, h, w;

  template <typename Fn>
  void for_neighbors(int32_t i, Fn fn) const {
    const int32_t plane = h * w;
    const int32_t z = i / plane, rem = i % plane, y = rem / w, x = rem % w;
    for (int32_t dz = -1; dz <= 1; ++dz) {
      const int32_t zz = z + dz;
      if (zz < 0 || zz >= nz) continue;
      for (int32_t dy = -1; dy <= 1; ++dy) {
        const int32_t yy = y + dy;
        if (yy < 0 || yy >= h) continue;
        for (int32_t dx = -1; dx <= 1; ++dx) {
          if (!dz && !dy && !dx) continue;
          const int32_t xx = x + dx;
          if (xx < 0 || xx >= w) continue;
          fn(zz * plane + yy * w + xx);
        }
      }
    }
  }
};

// Shared level-loop body of tm_watershed_levels / tm_watershed_levels3d.
//
// Semantics are identical to ops/segment_secondary.py's XLA path (and its
// 3-D twin): per level, every unlabeled admitted pixel simultaneously
// adopts the MAX label among its neighbors from the previous state,
// repeated to convergence, then one final pass admits the whole mask.
// Labels are immutable once assigned, so the Jacobi fixpoint equals a
// breadth-first wave where a pixel joins at the first wave in which it
// has a labeled neighbor.  Phase 1 reads only pre-wave labels; phase 2
// commits, keeping same-wave assignments invisible exactly like the
// vectorized jnp.where update.
//
// Complexity: a PERSISTENT candidate set (unlabeled mask pixels adjacent
// to the labeled region) carries over between levels and admission is
// tested lazily per candidate, so there is exactly ONE full-image scan
// (candidate seeding) instead of the naive two per level — per-level
// cost is O(|boundary|), not O(n).  Every pixel enters the candidate
// list at most once per discovery edge, preserving the wave order: at a
// level's start ALL admitted candidates enter the first wave together,
// exactly the set the Jacobi step would label first.
template <typename Geo>
void watershed_levels_impl(const float* intensity, const int32_t* seeds,
                           const uint8_t* mask, size_t n, Geo geo,
                           const float* levels, int32_t n_levels,
                           int32_t* out) {
  std::vector<int32_t> labels(seeds, seeds + n);
  std::vector<uint8_t> in_cand(n, 0), in_next(n, 0);
  std::vector<int32_t> candidates, frontier, next, adopted;

  // the one full scan: unlabeled mask pixels touching the seeded region
  for (size_t i = 0; i < n; ++i) {
    if (labels[i] != 0 || !mask[i]) continue;
    bool touch = false;
    geo.for_neighbors((int32_t)i, [&](int32_t q) { touch |= labels[q] != 0; });
    if (touch) { candidates.push_back((int32_t)i); in_cand[i] = 1; }
  }

  auto flood_level = [&](auto admitted) {
    // admitted candidates form the first wave; the rest stay candidates
    frontier.clear();
    size_t keep = 0;
    for (size_t k = 0; k < candidates.size(); ++k) {
      const int32_t p = candidates[k];
      if (labels[p] != 0) { in_cand[p] = 0; continue; }  // labeled later on
      if (admitted(p)) {
        in_cand[p] = 0;
        frontier.push_back(p);
      } else {
        candidates[keep++] = p;
      }
    }
    candidates.resize(keep);
    while (!frontier.empty()) {
      adopted.assign(frontier.size(), 0);
      for (size_t k = 0; k < frontier.size(); ++k) {
        int32_t best = 0;
        geo.for_neighbors(frontier[k], [&](int32_t q) {
          best = std::max(best, labels[q]);
        });
        adopted[k] = best;  // >0 by frontier construction
      }
      next.clear();
      for (size_t k = 0; k < frontier.size(); ++k)
        labels[frontier[k]] = adopted[k];
      for (size_t k = 0; k < frontier.size(); ++k) {
        geo.for_neighbors(frontier[k], [&](int32_t q) {
          if (labels[q] != 0 || !mask[q]) return;
          if (admitted(q)) {
            // remaining candidates are all non-admitted at this level,
            // so an admitted unlabeled neighbor can only be fresh
            if (!in_next[q]) { in_next[q] = 1; next.push_back(q); }
          } else if (!in_cand[q]) {
            in_cand[q] = 1;
            candidates.push_back(q);  // for a later (dimmer) level
          }
        });
      }
      for (size_t k = 0; k < next.size(); ++k) in_next[next[k]] = 0;
      frontier.swap(next);
    }
  };

  for (int32_t l = 0; l < n_levels; ++l) {
    const float level = levels[l];
    flood_level([&](int32_t p) { return intensity[p] >= level; });
  }
  // mop up below the lowest level (numerical edge)
  flood_level([](int32_t) { return true; });
  for (size_t i = 0; i < n; ++i) out[i] = mask[i] ? labels[i] : 0;
}

}  // namespace wsnative

extern "C" {

// Fill background holes: background regions (connectivity-connected) not
// reachable from the image border become foreground.  Matches
// ops/label.py fill_holes (scipy binary_fill_holes semantics at the
// default background connectivity 4).  Returns 0, or -1 on bad args.
int32_t tm_fill_holes(const uint8_t* mask, int32_t h, int32_t w,
                      int32_t connectivity, uint8_t* out) {
  if (!mask || !out || h <= 0 || w <= 0) return -1;
  if (connectivity != 4 && connectivity != 8) return -1;
  const size_t n = (size_t)h * (size_t)w;
  std::vector<uint8_t> reached(n, 0);
  std::vector<int32_t> stack;
  auto push = [&](int32_t y, int32_t x) {
    if (y < 0 || y >= h || x < 0 || x >= w) return;
    const size_t i = (size_t)y * w + x;
    if (mask[i] || reached[i]) return;
    reached[i] = 1;
    stack.push_back((int32_t)i);
  };
  for (int32_t x = 0; x < w; ++x) { push(0, x); push(h - 1, x); }
  for (int32_t y = 0; y < h; ++y) { push(y, 0); push(y, w - 1); }
  while (!stack.empty()) {
    const int32_t i = stack.back();
    stack.pop_back();
    const int32_t y = i / w, x = i % w;
    push(y - 1, x); push(y + 1, x); push(y, x - 1); push(y, x + 1);
    if (connectivity == 8) {
      push(y - 1, x - 1); push(y - 1, x + 1);
      push(y + 1, x - 1); push(y + 1, x + 1);
    }
  }
  for (size_t i = 0; i < n; ++i) out[i] = mask[i] || !reached[i];
  return 0;
}

// Chessboard distance-to-background, matching ops/segment_primary.py
// distance_transform_approx's erosion-counting semantics: with
// K = min(max_distance, max chebyshev distance in the image) erosions
// executed, every foreground pixel reads min(d, K + 1).  The image border
// is NOT background (binary_erode pads with foreground).  Two-pass
// chamfer, O(n).  Returns 0, or -1 on bad args.
int32_t tm_chebyshev_dt(const uint8_t* mask, int32_t h, int32_t w,
                        int32_t max_distance, float* out) {
  if (!mask || !out || h <= 0 || w <= 0 || max_distance < 0) return -1;
  const size_t n = (size_t)h * (size_t)w;
  const int32_t INF = h + w + 2;  // > any chebyshev distance in-image
  std::vector<int32_t> d(n);
  for (size_t i = 0; i < n; ++i) d[i] = mask[i] ? INF : 0;
  auto relax = [&](size_t i, size_t j) {
    if (d[j] + 1 < d[i]) d[i] = d[j] + 1;
  };
  for (int32_t y = 0; y < h; ++y) {
    for (int32_t x = 0; x < w; ++x) {
      const size_t i = (size_t)y * w + x;
      if (!d[i]) continue;
      if (x > 0) relax(i, i - 1);
      if (y > 0) {
        relax(i, i - w);
        if (x > 0) relax(i, i - w - 1);
        if (x + 1 < w) relax(i, i - w + 1);
      }
    }
  }
  int32_t max_d = 0;
  for (int32_t y = h - 1; y >= 0; --y) {
    for (int32_t x = w - 1; x >= 0; --x) {
      const size_t i = (size_t)y * w + x;
      if (!d[i]) continue;
      if (x + 1 < w) relax(i, i + 1);
      if (y + 1 < h) {
        relax(i, i + w);
        if (x + 1 < w) relax(i, i + w + 1);
        if (x > 0) relax(i, i + w - 1);
      }
      max_d = std::max(max_d, d[i]);
    }
  }
  // no background anywhere -> nothing ever erodes (the erosion pads with
  // foreground), so the XLA loop runs all max_distance iterations and
  // every pixel reads max_distance + 1
  const int32_t K = (max_d >= INF) ? max_distance
                                   : std::min(max_distance, max_d);
  for (size_t i = 0; i < n; ++i) {
    // an unreachable pixel (no background at all) survives every erosion:
    // its distance is effectively infinite, not the INF sentinel value
    const int32_t di = (d[i] >= INF) ? K + 1 : d[i];
    out[i] = (float)std::min(di, K + 1) * (d[i] ? 1.0f : 0.0f);
  }
  return 0;
}

// Level-ordered watershed flooding, bit-identical to
// ops/segment_secondary.py watershed_from_seeds (XLA path): for each
// threshold in `levels` (descending), flood seed labels into mask pixels
// with intensity >= level to convergence (synchronous max-label
// adoption), then one final flood admitting the whole mask.  The caller
// passes the level values computed by the SAME jitted expression the XLA
// path uses, so band membership is decided by exact float comparisons.
// Returns 0, or -1 on bad args.
int32_t tm_watershed_levels(const float* intensity, const int32_t* seeds,
                            const uint8_t* mask, int32_t h, int32_t w,
                            const float* levels, int32_t n_levels,
                            int32_t connectivity, int32_t* out) {
  if (!intensity || !seeds || !mask || !out || h <= 0 || w <= 0) return -1;
  if (n_levels < 0 || (n_levels > 0 && !levels)) return -1;
  if (connectivity != 4 && connectivity != 8) return -1;
  const size_t n = (size_t)h * (size_t)w;
  wsnative::watershed_levels_impl(intensity, seeds, mask, n,
                                  wsnative::Geo2{h, w, connectivity},
                                  levels, n_levels, out);
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// 3-D CPU-fallback segmentation kernels (round-3): the z-stack twins of the
// 2-D kernels above, routed in by ops/volume.py when the backend is cpu
// (the 3-D lax.while_loop fixpoints are just as pathological on XLA-CPU as
// the 2-D ones were — volume bench sat at 0.77x the scipy baseline).

extern "C" {

// 3-D union-find connected components, scipy scan order (component ids by
// first voxel in (z, y, x) row-major order).  connectivity: 6 faces,
// 18 faces+edges, 26 full.  Returns N, or -1 on bad args.
int32_t tm_cc_label3d(const uint8_t* mask, int32_t nz, int32_t h, int32_t w,
                      int32_t connectivity, int32_t* out) {
  if (!mask || !out || nz <= 0 || h <= 0 || w <= 0) return -1;
  if (connectivity != 6 && connectivity != 18 && connectivity != 26) return -1;
  const size_t n = (size_t)nz * h * w;
  const int32_t plane = h * w;
  // prior-neighbor offsets: lexicographically negative (dz,dy,dx) kept by
  // connectivity class (1 nonzero = faces, <=2 = edges, <=3 = corners)
  std::vector<std::array<int32_t, 3>> offs;
  for (int32_t dz = -1; dz <= 1; ++dz)
    for (int32_t dy = -1; dy <= 1; ++dy)
      for (int32_t dx = -1; dx <= 1; ++dx) {
        if (dz > 0 || (dz == 0 && (dy > 0 || (dy == 0 && dx >= 0)))) continue;
        const int32_t nonzero = (dz != 0) + (dy != 0) + (dx != 0);
        if (connectivity == 6 && nonzero > 1) continue;
        if (connectivity == 18 && nonzero > 2) continue;
        offs.push_back({dz, dy, dx});
      }
  UnionFind uf(n);
  for (int32_t z = 0; z < nz; ++z) {
    for (int32_t y = 0; y < h; ++y) {
      for (int32_t x = 0; x < w; ++x) {
        const size_t i = (size_t)z * plane + (size_t)y * w + x;
        if (!mask[i]) continue;
        for (const auto& o : offs) {
          const int32_t zz = z + o[0], yy = y + o[1], xx = x + o[2];
          if (zz < 0 || yy < 0 || yy >= h || xx < 0 || xx >= w) continue;
          const size_t j = (size_t)zz * plane + (size_t)yy * w + xx;
          if (mask[j]) uf.unite((int32_t)i, (int32_t)j);
        }
      }
    }
  }
  std::vector<int32_t> remap(n, 0);
  int32_t nextid = 0;
  for (size_t i = 0; i < n; ++i) {
    if (!mask[i]) { out[i] = 0; continue; }
    const int32_t r = uf.find((int32_t)i);
    if (remap[r] == 0) remap[r] = ++nextid;
    out[i] = remap[r];
  }
  return nextid;
}

// 3-D level-ordered watershed flooding, bit-identical to
// ops/volume.py watershed_from_seeds_3d (26-neighbor synchronous-wave
// adoption per level, then a whole-mask mop-up).  Returns 0 / -1.
int32_t tm_watershed_levels3d(const float* intensity, const int32_t* seeds,
                              const uint8_t* mask, int32_t nz, int32_t h,
                              int32_t w, const float* levels,
                              int32_t n_levels, int32_t* out) {
  if (!intensity || !seeds || !mask || !out || nz <= 0 || h <= 0 || w <= 0)
    return -1;
  if (n_levels < 0 || (n_levels > 0 && !levels)) return -1;
  const size_t n = (size_t)nz * h * w;
  wsnative::watershed_levels_impl(intensity, seeds, mask, n,
                                  wsnative::Geo3{nz, h, w},
                                  levels, n_levels, out);
  return 0;
}

// Per-label intensity accumulators over a (possibly plate-scale) label
// mosaic in ONE pass: sum, sum-of-squares (float64 accumulation, exactly
// matching the numpy float64 bincount twin), min, max.  Arrays are sized
// count + 1 with index 0 = background.  Returns 0, or -1 on bad args /
// a label outside [0, count] (corrupt input must not scribble memory).
int32_t tm_mosaic_intensity(const int32_t* labels, const float* vals,
                            int64_t n, int32_t count, double* sum_out,
                            double* sq_out, double* min_out,
                            double* max_out) {
  if (!labels || !vals || !sum_out || !sq_out || !min_out || !max_out ||
      n < 0 || count < 0)
    return -1;
  const double inf = std::numeric_limits<double>::infinity();
  for (int32_t k = 0; k <= count; ++k) {
    sum_out[k] = 0.0;
    sq_out[k] = 0.0;
    min_out[k] = inf;
    max_out[k] = -inf;
  }
  for (int64_t i = 0; i < n; ++i) {
    const int32_t l = labels[i];
    if (l < 0 || l > count) return -1;
    const double v = static_cast<double>(vals[i]);
    sum_out[l] += v;
    sq_out[l] += v * v;
    if (v < min_out[l]) min_out[l] = v;
    if (v > max_out[l]) max_out[l] = v;
  }
  return 0;
}

// Per-label morphology accumulators over a label mosaic in ONE pass:
// pixel area, centroid sums, and bounding boxes.  Arrays sized count + 1
// (index 0 = background); ymin/xmin start at h/w and ymax/xmax at -1 so
// absent labels keep the numpy twin's sentinels.  Returns 0 / -1.
int32_t tm_mosaic_morph(const int32_t* labels, int32_t h, int32_t w,
                        int32_t count, int64_t* area_out, double* cy_out,
                        double* cx_out, int64_t* ymin_out, int64_t* ymax_out,
                        int64_t* xmin_out, int64_t* xmax_out) {
  if (!labels || !area_out || !cy_out || !cx_out || !ymin_out || !ymax_out ||
      !xmin_out || !xmax_out || h <= 0 || w <= 0 || count < 0)
    return -1;
  for (int32_t k = 0; k <= count; ++k) {
    area_out[k] = 0;
    cy_out[k] = 0.0;
    cx_out[k] = 0.0;
    ymin_out[k] = h;
    ymax_out[k] = -1;
    xmin_out[k] = w;
    xmax_out[k] = -1;
  }
  for (int32_t y = 0; y < h; ++y) {
    const int32_t* row = labels + static_cast<int64_t>(y) * w;
    for (int32_t x = 0; x < w; ++x) {
      const int32_t l = row[x];
      if (l < 0 || l > count) return -1;
      area_out[l] += 1;
      cy_out[l] += y;
      cx_out[l] += x;
      if (y < ymin_out[l]) ymin_out[l] = y;
      if (y > ymax_out[l]) ymax_out[l] = y;
      if (x < xmin_out[l]) xmin_out[l] = x;
      if (x > xmax_out[l]) xmax_out[l] = x;
    }
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Round-5: per-SITE measurement accumulators.  The CPU backend's measure
// stage was scatter-bound (XLA-CPU lowers segment_sum/min/max to serial
// element scatters, ~2.3 ms/site at 256^2); one fused C pass computes all
// five per-label statistics for a whole site batch.

extern "C" {

// Per-label count / sum / sum-of-squares / min / max over a batch of label
// sites in ONE pass.  Accumulation is float32 in row-major pixel order —
// deliberately reproducing XLA-CPU's segment_sum/segment_min/segment_max
// (same adds, same order, multiply rounded before accumulate), so swapping
// the dispatch cannot move any downstream feature value.  Outputs are
// (n_sites, count + 1) row-major; index 0 = background; min/max start at
// +/-inf (the XLA reduction identities, kept for absent labels).  Labels
// outside [0, count] are DROPPED like the XLA scatter twin drops
// out-of-range segment ids (NOT an error: saturated sites legitimately
// carry clipped ids at the cap).  Returns 0, or -1 on null/negative args.
int32_t tm_site_stats(const int32_t* labels, const float* vals,
                      int64_t n_sites, int64_t px, int32_t count,
                      float* cnt_out, float* sum_out, float* sq_out,
                      float* min_out, float* max_out) {
  if (!labels || !vals || !cnt_out || !sum_out || !sq_out || !min_out ||
      !max_out || n_sites < 0 || px < 0 || count < 0)
    return -1;
  const float inf = std::numeric_limits<float>::infinity();
  const int64_t k1 = static_cast<int64_t>(count) + 1;
  for (int64_t s = 0; s < n_sites; ++s) {
    float* cnt = cnt_out + s * k1;
    float* sum = sum_out + s * k1;
    float* sq = sq_out + s * k1;
    float* mn = min_out + s * k1;
    float* mx = max_out + s * k1;
    for (int64_t k = 0; k < k1; ++k) {
      cnt[k] = 0.0f;
      sum[k] = 0.0f;
      sq[k] = 0.0f;
      mn[k] = inf;
      mx[k] = -inf;
    }
    const int32_t* lab = labels + s * px;
    const float* val = vals + s * px;
    for (int64_t i = 0; i < px; ++i) {
      const int32_t l = lab[i];
      if (l < 0 || l > count) continue;  // drop, like the XLA scatter
      const float x = val[i];
      const float xx = x * x;  // named temp: rounded, never fused (fma)
      cnt[l] += 1.0f;
      sum[l] += x;
      sq[l] += xx;
      if (x < mn[l]) mn[l] = x;
      if (x > mx[l]) mx[l] = x;
    }
  }
  return 0;
}

// Exact per-site histograms of int32 bin indices: counts accumulate as
// float32 (+1.0 adds are exact to 2^24 pixels/site); a negative index is
// normalized Python-style ONCE (+bins) and indices still out of range
// after that are dropped — all matching jnp's ``.at[idx].add`` scatter
// (ops/histogram.py method="scatter") bit-for-bit.  Outputs
// (n_sites, bins) row-major.  Returns 0 / -1 on null/invalid args.
int32_t tm_hist_counts(const int32_t* idx, int64_t n_sites, int64_t px,
                       int32_t bins, float* out) {
  if (!idx || !out || n_sites < 0 || px < 0 || bins <= 0) return -1;
  for (int64_t s = 0; s < n_sites; ++s) {
    float* row = out + s * static_cast<int64_t>(bins);
    for (int32_t b = 0; b < bins; ++b) row[b] = 0.0f;
    const int32_t* ix = idx + s * px;
    for (int64_t i = 0; i < px; ++i) {
      int32_t b = ix[i];
      if (b < 0) b += bins;  // jnp negative-index normalization
      if (b >= 0 && b < bins) row[b] += 1.0f;
    }
  }
  return 0;
}

}  // extern "C"

extern "C" {

// Fused per-site Otsu histogram: min/max plus the fixed-bin histogram of
// ((x - lo) / max(hi - lo, 1e-6)) * bins, truncated to int32 and clamped
// to [0, bins), in ONE pass over the pixels.  Every float operation is
// float32 with the same expression tree as the XLA path in
// ops/threshold.py otsu_value (sub, div, mul each rounded; int conversion
// truncates toward zero like XLA's ConvertElementType), and the build
// pins -ffp-contract=off, so the resulting histogram — and therefore the
// Otsu cut — is bit-identical.  Outputs: hist (n_sites, bins) float32,
// lo/hi (n_sites,) float32.  Returns 0 / -1 on bad args.
int32_t tm_otsu_hist(const float* img, int64_t n_sites, int64_t px,
                     int32_t bins, float* hist_out, float* lo_out,
                     float* hi_out) {
  if (!img || !hist_out || !lo_out || !hi_out || n_sites < 0 || px <= 0 ||
      bins <= 0)
    return -1;
  for (int64_t s = 0; s < n_sites; ++s) {
    const float* x = img + s * px;
    float lo = x[0], hi = x[0];
    for (int64_t i = 1; i < px; ++i) {
      if (x[i] < lo) lo = x[i];
      if (x[i] > hi) hi = x[i];
    }
    lo_out[s] = lo;
    hi_out[s] = hi;
    const float span_raw = hi - lo;
    const float span = span_raw > 1e-6f ? span_raw : 1e-6f;
    const float fbins = static_cast<float>(bins);
    float* hist = hist_out + s * static_cast<int64_t>(bins);
    for (int32_t b = 0; b < bins; ++b) hist[b] = 0.0f;
    for (int64_t i = 0; i < px; ++i) {
      const float a = x[i] - lo;     // each step rounded f32, like XLA
      const float r = a / span;
      const float c = r * fbins;
      int32_t b = static_cast<int32_t>(c);  // trunc toward zero
      if (b < 0) b = 0;
      if (b >= bins) b = bins - 1;
      hist[b] += 1.0f;
    }
  }
  return 0;
}

}  // extern "C"

extern "C" {

// Separable 2-D correlation over a batch of sites, bit-identical to the
// shifted-slice accumulation in ops/smooth.py _conv1d/uniform_smooth:
// per axis, out accumulates ky[i] * padded_slice_i with i ascending —
// each multiply rounded f32, each add rounded f32 (the build pins
// -ffp-contract=off), symmetric (numpy "symmetric") edge padding with
// ly/lx taps of pad on the leading side.  The kernels arrive as float32
// arrays COMPUTED BY the jitted caller, so there is no coefficient
// drift either.  Outputs (n_sites, h, w) float32.  Returns 0 / -1.
int32_t tm_sep_filter(const float* img, int64_t n_sites, int32_t h,
                      int32_t w, const float* ky, int32_t ny, int32_t ly,
                      const float* kx, int32_t nx, int32_t lx,
                      float* out) {
  if (!img || !ky || !kx || !out || n_sites < 0 || h <= 0 || w <= 0 ||
      ny <= 0 || nx <= 0 || ly < 0 || lx < 0 || ny - ly > h + 1 ||
      nx - lx > w + 1 || ly > h || lx > w)
    return -1;
  const int64_t px = static_cast<int64_t>(h) * w;
  std::vector<float> tmp(px);
  std::vector<float> row(static_cast<size_t>(w) + nx - 1);
  // numpy "symmetric": -1 -> 0, -2 -> 1, h -> h-1, h+1 -> h-2
  auto mirror = [](int32_t p, int32_t n) {
    if (p < 0) p = -p - 1;
    if (p >= n) p = 2 * n - 1 - p;
    return p;
  };
  for (int64_t s = 0; s < n_sites; ++s) {
    const float* in = img + s * px;
    // axis 0: tmp[y][x] = sum_i ky[i] * in[mirror(y + i - ly)][x]
    for (int32_t y = 0; y < h; ++y) {
      float* o = tmp.data() + static_cast<int64_t>(y) * w;
      for (int32_t x = 0; x < w; ++x) o[x] = 0.0f;
      for (int32_t i = 0; i < ny; ++i) {
        const float kv = ky[i];
        const float* src =
            in + static_cast<int64_t>(mirror(y + i - ly, h)) * w;
        for (int32_t x = 0; x < w; ++x) {
          const float prod = kv * src[x];  // rounded, never fused
          o[x] += prod;
        }
      }
    }
    // axis 1: out[y][x] = sum_i kx[i] * tmp[y][mirror(x + i - lx)]
    for (int32_t y = 0; y < h; ++y) {
      const float* t = tmp.data() + static_cast<int64_t>(y) * w;
      for (int32_t i = 0; i < nx - 1 + w; ++i)
        row[i] = t[mirror(i - lx, w)];
      float* o = out + s * px + static_cast<int64_t>(y) * w;
      for (int32_t x = 0; x < w; ++x) o[x] = 0.0f;
      for (int32_t i = 0; i < nx; ++i) {
        const float kv = kx[i];
        const float* src = row.data() + i;
        for (int32_t x = 0; x < w; ++x) {
          const float prod = kv * src[x];
          o[x] += prod;
        }
      }
    }
  }
  return 0;
}

}  // extern "C"

extern "C" {

// Separable box (mean) filter over a batch of sites, scipy
// uniform_filter semantics: per-axis running mean with "reflect"
// (numpy symmetric) borders, even windows biased one tap left, the
// axis-0 intermediate rounded to float32 like scipy's same-dtype
// intermediate.  O(1) work per pixel via double running sums (the
// unrolled XLA tap pass is O(size) — 31-tap windows dominated the
// adaptive-threshold module).  NOT bit-identical to the XLA taps
// (different accumulation order/precision) — threshold_adaptive's local
// mean is a tolerance-tier quantity, like the zernike host twin.
// Returns 0 / -1 on bad args (size must fit the image so a single
// mirror reflection covers the window).
int32_t tm_box_mean(const float* img, int64_t n_sites, int32_t h,
                    int32_t w, int32_t size, float* out) {
  if (!img || !out || n_sites < 0 || h <= 0 || w <= 0 || size <= 0 ||
      size > h || size > w)
    return -1;
  const int32_t left = size / 2;
  const int32_t right = size - left - 1;
  const double inv = 1.0 / static_cast<double>(size);
  const int64_t px = static_cast<int64_t>(h) * w;
  std::vector<float> tmp(px);
  std::vector<double> acc(w);
  auto mirror = [](int32_t p, int32_t n) {
    if (p < 0) p = -p - 1;
    if (p >= n) p = 2 * n - 1 - p;
    return p;
  };
  for (int64_t s = 0; s < n_sites; ++s) {
    const float* in = img + s * px;
    // axis 0: running column sums over the mirrored row window
    for (int32_t x = 0; x < w; ++x) acc[x] = 0.0;
    for (int32_t r = -left; r <= right; ++r) {
      const float* row = in + static_cast<int64_t>(mirror(r, h)) * w;
      for (int32_t x = 0; x < w; ++x) acc[x] += row[x];
    }
    for (int32_t y = 0; y < h; ++y) {
      float* t = tmp.data() + static_cast<int64_t>(y) * w;
      for (int32_t x = 0; x < w; ++x)
        t[x] = static_cast<float>(acc[x] * inv);
      if (y + 1 < h) {
        const float* add = in + static_cast<int64_t>(mirror(y + 1 + right, h)) * w;
        const float* sub = in + static_cast<int64_t>(mirror(y - left, h)) * w;
        for (int32_t x = 0; x < w; ++x) acc[x] += add[x] - sub[x];
      }
    }
    // axis 1: running sum along each (rounded) intermediate row
    for (int32_t y = 0; y < h; ++y) {
      const float* t = tmp.data() + static_cast<int64_t>(y) * w;
      float* o = out + s * px + static_cast<int64_t>(y) * w;
      double run = 0.0;
      for (int32_t c = -left; c <= right; ++c) run += t[mirror(c, w)];
      for (int32_t x = 0; x < w; ++x) {
        o[x] = static_cast<float>(run * inv);
        if (x + 1 < w)
          run += t[mirror(x + 1 + right, w)] - t[mirror(x - left, w)];
      }
    }
  }
  return 0;
}

}  // extern "C"

extern "C" {

// Multi-channel per-label sums over a batch of flattened sites:
// labels (n_sites, px) int32, vals (n_sites, n_channels, px) float32 →
// sums (n_sites, n_channels, count + 1) float32.  Accumulation is
// float32 in row-major pixel order per channel — XLA-CPU's
// segment_sum over (px, channels) stacks accumulates each channel
// column independently in pixel order, so this is bit-identical.
// Out-of-range labels are DROPPED like segment ids.  Returns 0 / -1.
int32_t tm_site_channel_sums(const int32_t* labels, const float* vals,
                             int64_t n_sites, int64_t n_channels,
                             int64_t px, int32_t count, float* sums_out) {
  if (!labels || !vals || !sums_out || n_sites < 0 || n_channels <= 0 ||
      px < 0 || count < 0)
    return -1;
  const int64_t k1 = static_cast<int64_t>(count) + 1;
  for (int64_t s = 0; s < n_sites; ++s) {
    const int32_t* lab = labels + s * px;
    for (int64_t c = 0; c < n_channels; ++c) {
      const float* v = vals + (s * n_channels + c) * px;
      float* out = sums_out + (s * n_channels + c) * k1;
      for (int64_t k = 0; k < k1; ++k) out[k] = 0.0f;
      for (int64_t i = 0; i < px; ++i) {
        const int32_t l = lab[i];
        if (l < 0 || l > count) continue;
        out[l] += v[i];
      }
    }
  }
  return 0;
}

// Multi-channel per-label (min, max), same layout/semantics as
// tm_site_channel_sums; absent labels keep the XLA reduction
// identities (+inf / -inf).  Returns 0 / -1.
int32_t tm_site_channel_minmax(const int32_t* labels, const float* vals,
                               int64_t n_sites, int64_t n_channels,
                               int64_t px, int32_t count, float* min_out,
                               float* max_out) {
  if (!labels || !vals || !min_out || !max_out || n_sites < 0 ||
      n_channels <= 0 || px < 0 || count < 0)
    return -1;
  const float inf = std::numeric_limits<float>::infinity();
  const int64_t k1 = static_cast<int64_t>(count) + 1;
  for (int64_t s = 0; s < n_sites; ++s) {
    const int32_t* lab = labels + s * px;
    for (int64_t c = 0; c < n_channels; ++c) {
      const float* v = vals + (s * n_channels + c) * px;
      float* mn = min_out + (s * n_channels + c) * k1;
      float* mx = max_out + (s * n_channels + c) * k1;
      for (int64_t k = 0; k < k1; ++k) {
        mn[k] = inf;
        mx[k] = -inf;
      }
      for (int64_t i = 0; i < px; ++i) {
        const int32_t l = lab[i];
        if (l < 0 || l > count) continue;
        const float x = v[i];
        if (x < mn[l]) mn[l] = x;
        if (x > mx[l]) mx[l] = x;
      }
    }
  }
  return 0;
}

}  // extern "C"

extern "C" {

// Per-object quantization + 4-direction GLCM accumulation in one native
// pass over a site batch.  Quantization replicates
// ops/measure.py quantize_per_object exactly: per-object min/max (pass
// 1), then floor(((v - lo) * (levels-1)) / max(span, 1e-6)) with each
// f32 step rounded separately (-ffp-contract=off) and clamped to
// [0, levels-1]; objects with no pixels never contribute.  GLCM counts
// are EXACT integers (f32 +1.0 adds, order-independent), accumulated
// for pixel pairs ((y, x), (y - dy, x - dx)) with equal nonzero labels
// — the same pairs ops/measure.py _glcm_scatter counts — and
// symmetrized (g + g^T).  Output layout:
// (n_sites, 4, count, levels, levels) float32, direction order
// (0,d), (d,0), (d,d), (d,-d).  Returns 0 / -1 on bad args.
int32_t tm_site_glcm(const int32_t* labels, const float* img,
                     int64_t n_sites, int32_t h, int32_t w, int32_t count,
                     int32_t levels, int32_t distance, float* glcm_out) {
  if (!labels || !img || !glcm_out || n_sites < 0 || h <= 0 || w <= 0 ||
      count < 0 || levels <= 1 || distance <= 0)
    return -1;
  const int64_t px = static_cast<int64_t>(h) * w;
  const int64_t ll = static_cast<int64_t>(levels) * levels;
  const int64_t per_site = 4 * static_cast<int64_t>(count) * ll;
  const float inf = std::numeric_limits<float>::infinity();
  std::vector<float> lo(count + 1), hi(count + 1);
  std::vector<uint8_t> q(px);
  const int32_t d = distance;
  const int32_t dys[4] = {0, d, d, d};
  const int32_t dxs[4] = {d, 0, d, -d};
  for (int64_t s = 0; s < n_sites; ++s) {
    const int32_t* lab = labels + s * px;
    const float* v = img + s * px;
    for (int32_t k = 0; k <= count; ++k) {
      lo[k] = inf;
      hi[k] = -inf;
    }
    for (int64_t i = 0; i < px; ++i) {
      const int32_t l = lab[i];
      if (l < 1 || l > count) continue;
      const float x = v[i];
      if (x < lo[l]) lo[l] = x;
      if (x > hi[l]) hi[l] = x;
    }
    // per-object stretch (quantize_per_object: lo=0/span=1 for absent,
    // span floor 1e-6; each op rounded f32)
    for (int64_t i = 0; i < px; ++i) {
      const int32_t l = lab[i];
      if (l < 1 || l > count) {
        q[i] = 0;  // background quantization is never read (pairs
                   // require equal labels > 0)
        continue;
      }
      const float present = hi[l] >= lo[l] ? 1.0f : 0.0f;
      const float lov = present ? lo[l] : 0.0f;
      const float span_raw = present ? (hi[l] - lov) : 1.0f;
      const float span = span_raw > 1e-6f ? span_raw : 1e-6f;
      const float a = v[i] - lov;
      const float b = a * static_cast<float>(levels - 1);
      const float c = b / span;
      float f = std::floor(c);
      if (f < 0.0f) f = 0.0f;
      if (f > static_cast<float>(levels - 1))
        f = static_cast<float>(levels - 1);
      q[i] = static_cast<uint8_t>(f);
    }
    float* gsite = glcm_out + s * per_site;
    for (int64_t i = 0; i < per_site; ++i) gsite[i] = 0.0f;
    for (int32_t dir = 0; dir < 4; ++dir) {
      const int32_t dy = dys[dir], dx = dxs[dir];
      float* g = gsite + static_cast<int64_t>(dir) * count * ll;
      for (int32_t y = 0; y < h; ++y) {
        const int32_t y2 = y - dy;
        if (y2 < 0 || y2 >= h) continue;
        const int32_t x_begin = dx > 0 ? dx : 0;
        const int32_t x_end = dx < 0 ? w + dx : w;
        const int32_t* lrow = lab + static_cast<int64_t>(y) * w;
        const int32_t* lrow2 = lab + static_cast<int64_t>(y2) * w;
        const uint8_t* qrow = q.data() + static_cast<int64_t>(y) * w;
        const uint8_t* qrow2 = q.data() + static_cast<int64_t>(y2) * w;
        for (int32_t x = x_begin; x < x_end; ++x) {
          const int32_t l = lrow[x];
          if (l < 1 || l > count || lrow2[x - dx] != l) continue;
          g[(static_cast<int64_t>(l) - 1) * ll + qrow[x] * levels +
            qrow2[x - dx]] += 1.0f;
        }
      }
      // symmetrize in place: g = g + g^T per object
      for (int32_t k = 0; k < count; ++k) {
        float* gm = g + static_cast<int64_t>(k) * ll;
        for (int32_t i = 0; i < levels; ++i)
          for (int32_t j = i; j < levels; ++j) {
            const float sum = gm[i * levels + j] + gm[j * levels + i];
            gm[i * levels + j] = sum;
            gm[j * levels + i] = sum;
          }
      }
    }
  }
  return 0;
}

}  // extern "C"
