// tmnative: first-party native host kernels.
//
// Reference parity: the reference's performance-critical host code lives in
// third-party C++ (cv2, mahotas — SURVEY.md §3 "external binary deps"); the
// TPU rebuild keeps device math in XLA and implements its own native host
// kernels for the two pathways that stay on the CPU:
//
//   1. union-find connected-component labeling (scipy scan order) — the
//      host-side golden/fallback for the device labeler and the fast path
//      for host-only workflows (ingest QC, tests);
//   2. Moore-neighbor boundary tracing — polygon extraction for the object
//      store (reference: PostGIS polygons via shapely/cv2).
//
// Built as a plain shared library, loaded via ctypes (no pybind11 in the
// image). C ABI only.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <utility>
#include <vector>

namespace {

struct UnionFind {
  std::vector<int32_t> parent;
  explicit UnionFind(size_t n) : parent(n) {
    for (size_t i = 0; i < n; ++i) parent[i] = static_cast<int32_t>(i);
  }
  int32_t find(int32_t x) {
    while (parent[x] != x) {
      parent[x] = parent[parent[x]];  // path halving
      x = parent[x];
    }
    return x;
  }
  void unite(int32_t a, int32_t b) {
    a = find(a);
    b = find(b);
    if (a == b) return;
    // keep the smaller root: scan-order labeling falls out of this
    if (a < b) parent[b] = a; else parent[a] = b;
  }
};

}  // namespace

extern "C" {

// Label the foreground (mask != 0) with 4- or 8-connectivity.
// labels_out receives 0 for background, 1..N in scipy scan order
// (components numbered by first pixel in row-major order).
// Returns N, or -1 on invalid arguments.
int32_t tm_cc_label(const uint8_t* mask, int32_t h, int32_t w,
                    int32_t connectivity, int32_t* labels_out) {
  if (!mask || !labels_out || h <= 0 || w <= 0) return -1;
  if (connectivity != 4 && connectivity != 8) return -1;
  const size_t n = static_cast<size_t>(h) * static_cast<size_t>(w);
  UnionFind uf(n);

  // one pass of neighbor unions (only look up/left — prior pixels)
  for (int32_t y = 0; y < h; ++y) {
    for (int32_t x = 0; x < w; ++x) {
      const size_t i = static_cast<size_t>(y) * w + x;
      if (!mask[i]) continue;
      if (x > 0 && mask[i - 1]) uf.unite(static_cast<int32_t>(i), static_cast<int32_t>(i - 1));
      if (y > 0) {
        const size_t up = i - w;
        if (mask[up]) uf.unite(static_cast<int32_t>(i), static_cast<int32_t>(up));
        if (connectivity == 8) {
          if (x > 0 && mask[up - 1]) uf.unite(static_cast<int32_t>(i), static_cast<int32_t>(up - 1));
          if (x + 1 < w && mask[up + 1]) uf.unite(static_cast<int32_t>(i), static_cast<int32_t>(up + 1));
        }
      }
    }
  }

  // second pass: roots are component minima (smaller-root union), so
  // numbering roots in scan order reproduces scipy.ndimage.label exactly
  std::vector<int32_t> remap(n, 0);
  int32_t next = 0;
  for (size_t i = 0; i < n; ++i) {
    if (!mask[i]) { labels_out[i] = 0; continue; }
    const int32_t r = uf.find(static_cast<int32_t>(i));
    if (remap[r] == 0) remap[r] = ++next;
    labels_out[i] = remap[r];
  }
  return next;
}

// Moore-neighbor boundary trace of one labeled object (8-connected
// boundary, clockwise, starting at the first pixel in scan order).
// out_yx receives up to max_pts (y, x) pairs; returns the number of
// points, 0 if the label is absent, or -1 on invalid arguments.
int32_t tm_trace_boundary(const int32_t* labels, int32_t h, int32_t w,
                          int32_t label, int32_t* out_yx, int32_t max_pts) {
  if (!labels || !out_yx || h <= 0 || w <= 0 || max_pts <= 0) return -1;
  auto at = [&](int32_t y, int32_t x) -> bool {
    return y >= 0 && y < h && x >= 0 && x < w &&
           labels[static_cast<size_t>(y) * w + x] == label;
  };
  // first pixel in scan order
  int32_t sy = -1, sx = -1;
  for (int32_t y = 0; y < h && sy < 0; ++y)
    for (int32_t x = 0; x < w; ++x)
      if (at(y, x)) { sy = y; sx = x; break; }
  if (sy < 0) return 0;

  // clockwise Moore neighborhood order: W, NW, N, NE, E, SE, S, SW
  static const int32_t dy[8] = {0, -1, -1, -1, 0, 1, 1, 1};
  static const int32_t dx[8] = {-1, -1, 0, 1, 1, 1, 0, -1};

  // Moore tracing with explicit backtrack + Jacob's stopping criterion:
  // stop when the start pixel is re-entered from its original backtrack.
  int32_t cy = sy, cx = sx;
  int32_t back = 0;  // direction from current to backtrack; start = west
  const int32_t back0 = back;
  int32_t count = 0;
  const int64_t limit = static_cast<int64_t>(h) * w * 4 + 8;
  for (int64_t iter = 0; iter < limit; ++iter) {
    if (iter == 0 || !(cy == sy && cx == sx)) {
      if (count < max_pts) {
        out_yx[2 * count] = cy;
        out_yx[2 * count + 1] = cx;
      }
      ++count;
    }
    // scan clockwise from just past the backtrack neighbor
    int32_t k = 1;
    int32_t d = -1;
    for (; k <= 8; ++k) {
      d = (back + k) % 8;
      if (at(cy + dy[d], cx + dx[d])) break;
    }
    if (k > 8) break;  // isolated pixel
    // move; the new backtrack is the neighbor scanned just before d,
    // expressed as a direction from the NEW pixel
    const int32_t prev = (back + k - 1) % 8;
    const int32_t py = cy + dy[prev], px = cx + dx[prev];
    cy += dy[d];
    cx += dx[d];
    // direction from new current back to that previous (background) pixel
    back = 0;
    for (int32_t j = 0; j < 8; ++j) {
      if (cy + dy[j] == py && cx + dx[j] == px) { back = j; break; }
    }
    if (cy == sy && cx == sx && back == back0) break;
  }
  // return the TRUE count even when it exceeds max_pts, so callers can
  // detect truncation and retry with a larger buffer
  return count;
}

// Per-object bounding boxes: out receives (min_y, min_x, max_y, max_x) per
// label 1..max_label (rows of 4); labels absent get (-1,-1,-1,-1).
void tm_bounding_boxes(const int32_t* labels, int32_t h, int32_t w,
                       int32_t max_label, int32_t* out) {
  for (int32_t l = 0; l < max_label; ++l) {
    out[4 * l] = -1; out[4 * l + 1] = -1; out[4 * l + 2] = -1; out[4 * l + 3] = -1;
  }
  for (int32_t y = 0; y < h; ++y) {
    for (int32_t x = 0; x < w; ++x) {
      const int32_t v = labels[static_cast<size_t>(y) * w + x];
      if (v < 1 || v > max_label) continue;
      int32_t* b = out + 4 * (v - 1);
      if (b[0] < 0) { b[0] = y; b[1] = x; b[2] = y; b[3] = x; }
      else {
        if (y < b[0]) b[0] = y;
        if (x < b[1]) b[1] = x;
        if (y > b[2]) b[2] = y;
        if (x > b[3]) b[3] = x;
      }
    }
  }
}

// Per-object rasterized convex hull pixel counts (skimage
// convex_hull_image semantics over pixel centers): for each label
// 1..max_label, out[l-1] receives the number of pixels whose center lies
// inside or on the convex hull of the object's pixel centers.  Labels
// absent get 0.  Solidity = area / hull_count falls out on the caller
// side.  Returns 0, or -1 on invalid arguments.
int32_t tm_hull_pixel_counts(const int32_t* labels, int32_t h, int32_t w,
                             int32_t max_label, int32_t* out) {
  if (!labels || !out || h <= 0 || w <= 0 || max_label <= 0) return -1;
  std::memset(out, 0, sizeof(int32_t) * static_cast<size_t>(max_label));

  // gather per-label bounding boxes + pixel lists in one scan
  std::vector<int32_t> bbox(static_cast<size_t>(max_label) * 4);
  for (int32_t l = 0; l < max_label; ++l) {
    bbox[4 * l] = -1; bbox[4 * l + 1] = -1; bbox[4 * l + 2] = -1; bbox[4 * l + 3] = -1;
  }
  std::vector<std::vector<std::pair<int32_t, int32_t>>> pts(max_label);
  for (int32_t y = 0; y < h; ++y) {
    for (int32_t x = 0; x < w; ++x) {
      const int32_t v = labels[static_cast<size_t>(y) * w + x];
      if (v < 1 || v > max_label) continue;
      int32_t* b = &bbox[4 * (v - 1)];
      if (b[0] < 0) { b[0] = y; b[1] = x; b[2] = y; b[3] = x; }
      else {
        if (y < b[0]) b[0] = y;
        if (x < b[1]) b[1] = x;
        if (y > b[2]) b[2] = y;
        if (x > b[3]) b[3] = x;
      }
      pts[v - 1].emplace_back(x, y);
    }
  }

  auto cross = [](int64_t ox, int64_t oy, int64_t ax, int64_t ay,
                  int64_t bx, int64_t by) -> int64_t {
    return (ax - ox) * (by - oy) - (ay - oy) * (bx - ox);
  };

  for (int32_t l = 0; l < max_label; ++l) {
    auto& p = pts[l];
    const size_t n = p.size();
    if (n == 0) continue;
    if (n <= 2) { out[l] = static_cast<int32_t>(n); continue; }
    // Andrew's monotone chain (points are already sorted by (y, x) from the
    // scan; re-sort by (x, y) as the algorithm expects)
    std::sort(p.begin(), p.end());
    std::vector<std::pair<int32_t, int32_t>> hull(2 * n);
    size_t k = 0;
    for (size_t i = 0; i < n; ++i) {            // lower hull
      while (k >= 2 && cross(hull[k - 2].first, hull[k - 2].second,
                             hull[k - 1].first, hull[k - 1].second,
                             p[i].first, p[i].second) <= 0) --k;
      hull[k++] = p[i];
    }
    for (size_t i = n - 1, t = k + 1; i-- > 0;) {  // upper hull
      while (k >= t && cross(hull[k - 2].first, hull[k - 2].second,
                             hull[k - 1].first, hull[k - 1].second,
                             p[i].first, p[i].second) <= 0) --k;
      hull[k++] = p[i];
    }
    hull.resize(k - 1);  // last point == first point
    const size_t m = hull.size();
    if (m <= 2) {  // degenerate (collinear object): hull pixels = object pixels
      out[l] = static_cast<int32_t>(n);
      continue;
    }
    // hull is counter-clockwise in (x, y) with cross<=0 popped: a pixel
    // center is inside-or-on iff it is left of (cross >= 0) every edge
    const int32_t* b = &bbox[4 * l];
    int32_t count = 0;
    for (int32_t y = b[0]; y <= b[2]; ++y) {
      for (int32_t x = b[1]; x <= b[3]; ++x) {
        bool inside = true;
        for (size_t i = 0; i < m && inside; ++i) {
          const auto& a0 = hull[i];
          const auto& a1 = hull[(i + 1) % m];
          if (cross(a0.first, a0.second, a1.first, a1.second, x, y) < 0)
            inside = false;
        }
        if (inside) ++count;
      }
    }
    out[l] = count;
  }
  return 0;
}

}  // extern "C"
